"""Llama-style decoder for the GSPMD graduation config (SURVEY.md §6
config ⑤: ``pjit``/GSPMD Llama-2-7B on a pod slice).

TPU-first design:

* bf16 compute / f32 params, RMSNorm in f32 (numerics), rotary embeddings,
  grouped-query attention, SwiGLU MLP — matmul shapes stay MXU-friendly
  multiples of 128 in the real configs;
* every parameter carries flax *logical* axis names
  (``nn.with_logical_partitioning``); :data:`tony_tpu.parallel.RULES` maps
  them to the dp/fsdp/tp mesh so GSPMD inserts the tensor-parallel
  collectives — no hand-written allreduce;
* attention dispatches through :func:`tony_tpu.ops.flash_attention` (fused
  pallas kernel on TPU) or :func:`tony_tpu.parallel.ring_attention_sharded`
  when the sequence axis is sharded (long context, SURVEY.md §5.7);
* ``scan_layers`` folds the layer stack into one ``nn.scan`` (one trace +
  one compile of a single block) and ``remat`` wraps blocks in
  ``jax.checkpoint`` to trade FLOPs for HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tony_tpu.models import register
from tony_tpu.ops import flash_attention, reference_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    ffn_hidden: int = 11008
    max_seq: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    attention: str = "flash"        # flash | ring | reference
    scan_layers: bool = True
    remat: bool = True
    # Rematerialization policy: None = full recompute (max memory saving,
    # ~4/3 extra executed FLOPs the matmul-only MFU accounting does not
    # credit); "dots" = jax.checkpoint_policies.checkpoint_dots (save all
    # matmul outputs, recompute only elementwise/norm/softmax — the
    # standard transformer trade).
    remat_policy: Optional[str] = None
    mesh: Optional[Any] = None      # required for attention="ring"
    # MoE (SURVEY.md §2.3 expert parallelism): >0 swaps the dense MLP for
    # an expert-parallel MoEMLP in every block.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01
    # >0 fuses the LM head with a row-chunked cross entropy: the [B,T,V]
    # logits tensor (f32: 4 GB at b64·s512·v32k) never materializes —
    # per-chunk logits are consumed immediately and rematerialized in the
    # backward. __call__ then takes targets and returns the scalar loss.
    xent_chunk: int = 0
    # Quantized compute lane (tony_tpu.ops.quant): which projection
    # groups run int8×int8→int32 matmuls with f32 rescale. True =
    # ("qkv", "o", "mlp"); a tuple selects explicitly ("lm_head" opts
    # the unembed in). Embedding and norms stay bf16/f32 by policy. The
    # lane is loss-pin gated: tests/test_quant.py holds the quantized
    # tiny-transformer curve against bf16 within a committed tolerance.
    quant: Any = None

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def quant_lanes(self) -> frozenset:
        """The validated set of quantized projection groups."""
        if not self.quant:
            return frozenset()
        lanes = ("qkv", "o", "mlp") if self.quant is True else (
            (self.quant,) if isinstance(self.quant, str)
            else tuple(self.quant))
        unknown = set(lanes) - {"qkv", "o", "mlp", "lm_head"}
        if unknown:
            raise ValueError(
                f"unknown quant lane(s) {sorted(unknown)} — choose from "
                f"('qkv', 'o', 'mlp', 'lm_head')")
        if "lm_head" in lanes and self.xent_chunk:
            raise ValueError(
                "quant lane 'lm_head' is not supported with xent_chunk "
                "(the fused head+loss consumes the kernel row-chunked; "
                "quantize it separately or drop the lane)")
        return frozenset(lanes)

    def flops_per_token(self) -> int:
        """≈6·N_matmul FLOPs per trained token (fwd+bwd), plus attention's
        12·L·dim·seq term — matmul-FLOPs-only MFU accounting. The input
        embedding is a gather (backward: scatter-add) and contributes zero
        matmul FLOPs, so only the unembed projection counts toward the
        vocab term. For MoE, only the top-k experts' FFN params are
        active per token."""
        ffn_active = 3 * self.dim * self.ffn_hidden
        if self.moe_experts > 0:
            ffn_active = (self.moe_top_k * ffn_active
                          + self.dim * self.moe_experts)  # + router
        n_params = (
            self.vocab * self.dim  # unembed only; embed gather = 0 matmul FLOPs
            + self.n_layers * (
                self.dim * self.head_dim
                * (self.n_heads + 2 * self.n_kv_heads)   # wq, wk, wv
                + self.n_heads * self.head_dim * self.dim  # wo
                + ffn_active))
        return 6 * n_params + 12 * self.n_layers * self.dim * self.max_seq


def rope(x: jax.Array, positions: jax.Array, theta: float,
         seq_axis: int = 2) -> jax.Array:
    """Rotary embedding with positions [T] (shared across the batch) or
    [B, T] (per-sequence absolute positions — the serving plane's decode
    rows sit at different depths per sequence); the sequence dim sits at
    ``seq_axis`` (2 for [B, H, T, D], 1 for the packed [B, T, H, D])."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    if positions.ndim == 2:
        angles = positions[..., None].astype(jnp.float32) * freqs  # [B,T,D/2]
        shape = [1] * x.ndim
        shape[0] = angles.shape[0]
        shape[seq_axis] = angles.shape[1]
        shape[-1] = d // 2
    else:
        angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [T, D/2]
        shape = [1] * x.ndim
        shape[seq_axis] = angles.shape[0]
        shape[-1] = d // 2
    cos = jnp.cos(angles).reshape(shape)
    sin = jnp.sin(angles).reshape(shape)
    x1, x2 = x[..., ::2], x[..., 1::2]
    y1 = x1 * cos - x2 * sin
    y2 = x1 * sin + x2 * cos
    # packsite: region-local — elementwise RoPE recombination along a
    # NEW trailing axis; operands share one sharding, no shard-dim concat.
    return jnp.stack([y1, y2], axis=-1).reshape(x.shape).astype(x.dtype)


def _proj_dense(cfg: TransformerConfig, lane: str, feats: int,
                logical: Tuple[str, ...], name: str):
    """One projection on either compute lane: ``nn.Dense`` (bf16 MXU) or
    its quantized twin (int8 MXU, f32 rescale) when ``lane`` is in the
    config's quant set — identical param tree paths either way, so a
    checkpoint moves freely between the lanes."""
    init = nn.with_logical_partitioning(
        nn.initializers.lecun_normal(), logical)
    if lane in cfg.quant_lanes():
        from tony_tpu.ops.quant import QuantDense
        return QuantDense(feats, dtype=cfg.dtype,
                          param_dtype=jnp.float32, name=name,
                          kernel_init=init)
    return nn.Dense(feats, use_bias=False, dtype=cfg.dtype,
                    param_dtype=jnp.float32, name=name, kernel_init=init)


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.with_logical_partitioning(
            nn.initializers.ones, ("norm",)), (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        y = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (y * scale).astype(x.dtype)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, kv=None):
        cfg = self.cfg
        b, t, _ = x.shape
        hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
        # Fused-head projections with rank-2 kernels: (embed, heads·hd)
        # sharded ('fsdp', 'model') — the megatron TP layout. (DenseGeneral's
        # multi-dim features initialize flat then reshape, which breaks
        # logical-metadata unboxing under an active mesh.)
        dense = lambda feats, logical, name, lane: _proj_dense(
            cfg, lane, feats, logical, name)
        q = dense(nh * hd, ("embed", "heads"), "wq", "qkv")(x)
        k = dense(nkv * hd, ("embed", "kv_heads"), "wk", "qkv")(x)
        v = dense(nkv * hd, ("embed", "kv_heads"), "wv", "qkv")(x)
        if kv is not None:
            # Serve-mode forward (tony_tpu.serve): the t rows are NEW
            # tokens at per-sequence absolute ``positions`` [b, t]; the
            # context lives in the gathered KV buffer [b, ctx, nkv·hd].
            # The rows' post-rope k/v scatter into the buffer (so a row
            # attends itself and everything the cache holds below its
            # position), attention runs through the flash-decoding
            # kernel, and the raw rows are returned for the engine to
            # commit into the paged pool. Projections are the SAME
            # denses as training — the quant= lanes ride along — so a
            # training checkpoint serves without any param surgery.
            # Prefill, decode, AND speculative k+1-row verification
            # (serve.spec) are all this one branch at different real-row
            # counts: the scatter-before-attend order is what lets a
            # verify row attend the draft rows below it in the same
            # launch, and the in-buffer overwrite of positions >= each
            # row's own block start is what makes rolled-back (stale)
            # pool rows unreadable by construction.
            k_buf, v_buf = kv
            pos = positions.astype(jnp.int32)
            q4 = rope(q.reshape(b, t, nh, hd), pos, cfg.rope_theta,
                      seq_axis=1)
            k4 = rope(k.reshape(b, t, nkv, hd), pos, cfg.rope_theta,
                      seq_axis=1)
            k_rows = k4.reshape(b, t, nkv * hd).astype(k_buf.dtype)
            v_rows = v.astype(v_buf.dtype)
            bidx = jnp.arange(b)[:, None]
            # mode="drop": rows whose position falls off the buffer end
            # (the trailing padding rows of a decode block near ctx_max)
            # simply don't write.
            k_buf = k_buf.at[bidx, pos].set(k_rows, mode="drop")
            v_buf = v_buf.at[bidx, pos].set(v_rows, mode="drop")
            ctx = k_buf.shape[1]
            from tony_tpu.ops import flash_decode
            out = flash_decode(
                q4.transpose(0, 2, 1, 3),
                k_buf.reshape(b, ctx, nkv, hd).transpose(0, 2, 1, 3),
                v_buf.reshape(b, ctx, nkv, hd).transpose(0, 2, 1, 3),
                pos)
            out = out.transpose(0, 2, 1, 3).reshape(b, t, nh * hd)
            return (dense(cfg.dim, ("heads", "embed"), "wo", "o")(out),
                    (k_rows, v_rows))
        if (cfg.attention == "flash" and cfg.mesh is None
                and hd % 128 == 0):
            # Packed layout: the kernel reads heads as lane offsets from
            # the projections' natural [B, T, H·D] shape — the [B, H, T, D]
            # transpose copies (profiled ~5% of the Llama step) never
            # materialize.
            from tony_tpu.ops import flash_attention_packed
            q4 = rope(q.reshape(b, t, nh, hd), positions, cfg.rope_theta,
                      seq_axis=1)
            k4 = rope(k.reshape(b, t, nkv, hd), positions, cfg.rope_theta,
                      seq_axis=1)
            # GQA is zero-copy through the packed kernels: K/V stay at
            # [B, T, nkv·hd]; the kernel's index maps route query head h
            # to kv lane-block h·nkv/nh (VERDICT r4 next-step #5 — no
            # jnp.repeat, no phantom-head HBM).
            out = flash_attention_packed(
                q4.reshape(b, t, nh * hd), k4.reshape(b, t, nkv * hd), v,
                nh, causal=True)
            return dense(cfg.dim, ("heads", "embed"), "wo", "o")(out)
        # [B, T, H·D] → [B, H, T, D]
        q = q.reshape(b, t, nh, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, t, nkv, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, t, nkv, hd).transpose(0, 2, 1, 3)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        # No GQA repeat on ANY path: the flash kernels, ring attention, and
        # reference_attention are all GQA-native (r5) — ring even ships
        # the narrow K/V around the ICI ring, dividing rotate traffic by
        # the group size.
        if cfg.attention == "ring":
            from tony_tpu.parallel import ring_attention_sharded
            assert cfg.mesh is not None, "attention='ring' needs cfg.mesh"
            out = ring_attention_sharded(q, k, v, cfg.mesh, causal=True)
        elif cfg.attention == "flash":
            if cfg.mesh is not None and cfg.mesh.shape.get("seq", 1) > 1:
                # A sharded sequence axis means per-device flash would be
                # wrong (causal attention needs global K/V) — ring
                # attention owns that layout.
                from tony_tpu.parallel import ring_attention_sharded
                out = ring_attention_sharded(q, k, v, cfg.mesh, causal=True)
            elif cfg.mesh is not None:
                # GSPMD can't partition a pallas call from annotations
                # alone — explicitly map it (heads on the tp axis).
                from tony_tpu.ops import flash_attention_sharded
                out = flash_attention_sharded(q, k, v, cfg.mesh, causal=True)
            else:
                out = flash_attention(q, k, v, causal=True)
        else:
            out = reference_attention(q, k, v, causal=True)
        out = out.transpose(0, 2, 1, 3).reshape(b, t, nh * hd)
        return dense(cfg.dim, ("heads", "embed"), "wo", "o")(out)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda feats, logical, name: _proj_dense(
            cfg, "mlp", feats, logical, name)
        gate = dense(cfg.ffn_hidden, ("embed", "ffn"), "w_gate")(x)
        up = dense(cfg.ffn_hidden, ("embed", "ffn"), "w_up")(x)
        y = nn.silu(gate) * up
        return dense(cfg.dim, ("ffn", "embed"), "w_down")(y)


class Block(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, kv=None):
        cfg = self.cfg
        attn_out = Attention(cfg, name="attn")(
            RMSNorm(cfg.norm_eps, name="attn_norm")(x), positions, kv=kv)
        new_kv = None
        if kv is not None:
            attn_out, new_kv = attn_out
        x = x + attn_out
        if cfg.moe_experts > 0:
            from tony_tpu.models.moe import MoEMLP
            mlp = MoEMLP(cfg.dim, cfg.ffn_hidden, cfg.moe_experts,
                         top_k=cfg.moe_top_k,
                         capacity_factor=cfg.moe_capacity_factor,
                         aux_coef=cfg.moe_aux_coef, dtype=cfg.dtype,
                         name="moe_mlp")
        else:
            mlp = MLP(cfg, name="mlp")
        x = x + mlp(RMSNorm(cfg.norm_eps, name="mlp_norm")(x))
        if kv is not None:
            return x, new_kv
        return x


class ScannedBlock(nn.Module):
    """Carry-signature wrapper so the layer stack folds into one
    ``nn.scan`` (single-block trace/compile, stacked params on a leading
    ``stage`` axis). In serve mode the per-layer KV buffer arrives as a
    scanned input and the freshly-written rows leave as the scan's
    stacked ys."""
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, kv=None):
        if kv is not None:
            return Block(self.cfg, name="block")(x, positions, kv=kv)
        return Block(self.cfg, name="block")(x, positions), None


class Transformer(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, targets=None, *, positions=None, kv=None):
        cfg = self.cfg
        _b, t = tokens.shape
        if kv is not None:
            # Serve-mode forward (tony_tpu.serve.engine): tokens are a
            # row block of NEW positions per sequence, context comes from
            # the gathered KV buffers (one [b, ctx, nkv·hd] pair per
            # layer, stacked on a leading layer axis), and the return is
            # ``(logits, (k_rows, v_rows))`` for the engine to commit
            # into its paged pool. Training traces are untouched: this
            # branch only exists when the engine passes kv.
            if targets is not None:
                raise ValueError("serve-mode forward takes no targets")
            if positions is None:
                raise ValueError("serve-mode forward needs positions "
                                 "[b, t] (per-sequence absolute)")
            if cfg.moe_experts > 0:
                raise ValueError("serve mode does not support MoE blocks")
        embed = self.param("embedding", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab, cfg.dim), jnp.float32)
        from flax.linen.spmd import get_logical_axis_rules

        def _sharded_training() -> bool:
            # True only when the rules context can actually shard the
            # table: a live axis-rules context AND a >1-device mesh in
            # scope (the train harness enters jax.set_mesh(mesh) around
            # its jit). jax.device_count() is NOT the right signal — a
            # single-device mesh on a multi-device host (or the CPU test
            # env's 8 virtual devices with an unsharded harness) must
            # keep the gather.
            if not get_logical_axis_rules():
                return False
            from tony_tpu.compat import ambient_mesh_size
            return ambient_mesh_size() > 1

        if _sharded_training():
            # Sharded multi-device training only — on one device the
            # one-hot costs ~18 ms/step of uncounted work at the bench
            # shape (found as a 4.3-MFU-pt regression in r5; the train
            # harness applies the rules context even unsharded): look up
            # via one-hot matmul, not gather. The table is (vocab→model,
            # embed→fsdp)-sharded while activations want batch over
            # (data, fsdp) — GSPMD reshard s dots cleanly (psum over the
            # contracted vocab axis + reduce-scatter) but a gather's
            # embed-fsdp→batch-fsdp transition is an "involuntary full
            # rematerialization": replicate-then-slice EVERY step, fwd and
            # transpose (MULTICHIP_r04 tail; VERDICT r4 next-step #3). The
            # one-hot term is 2·vocab·dim FLOPs/token ≈ 0.6% of a 7B step,
            # and it rides the MXU.
            x = jax.nn.one_hot(tokens, cfg.vocab, dtype=cfg.dtype) \
                @ embed.astype(cfg.dtype)
        else:
            x = jnp.take(embed, tokens, axis=0).astype(cfg.dtype)
        x = nn.with_logical_constraint(x, ("batch", "act_seq", "act_embed"))
        if positions is None:
            positions = jnp.arange(t)

        block_cls = ScannedBlock
        # Validated OUTSIDE the remat gate: a typo'd (or remat=False-
        # orphaned) policy must fail loudly, not silently not-apply.
        policy = None
        if cfg.remat_policy == "dots":
            policy = jax.checkpoint_policies.checkpoint_dots
        elif cfg.remat_policy == "dots_no_batch":
            policy = (jax.checkpoint_policies
                      .dots_with_no_batch_dims_saveable)
        elif cfg.remat_policy is not None:
            raise ValueError(f"unknown remat_policy {cfg.remat_policy!r}")
        if cfg.remat_policy is not None and not cfg.remat:
            raise ValueError("remat_policy set but remat=False")
        if cfg.remat:
            block_cls = nn.remat(block_cls, prevent_cse=False,
                                 policy=policy)
        new_kv = None
        if cfg.scan_layers:
            if kv is not None:
                # The per-layer KV buffers ride the scan as a sliced
                # input (in_axes 0 on the layer axis); the fresh rows
                # come back as the stacked ys — no explicit jnp.stack,
                # so no pack site.
                x, new_kv = nn.scan(
                    block_cls,
                    variable_axes={"params": 0, "losses": 0},
                    split_rngs={"params": True},
                    in_axes=(nn.broadcast, 0),
                    length=cfg.n_layers,
                    metadata_params={nn.PARTITION_NAME: "stage"},
                )(cfg, name="layers")(x, positions, kv)
            else:
                x, _ = nn.scan(
                    block_cls,
                    variable_axes={"params": 0, "losses": 0},
                    split_rngs={"params": True},
                    in_axes=nn.broadcast,
                    length=cfg.n_layers,
                    metadata_params={nn.PARTITION_NAME: "stage"},
                )(cfg, name="layers")(x, positions)
        else:
            if kv is not None:
                ks, vs = [], []
                for i in range(cfg.n_layers):
                    x, (kr, vr) = block_cls(cfg, name=f"layer_{i}")(
                        x, positions, jax.tree.map(lambda a: a[i], kv))
                    ks.append(kr)
                    vs.append(vr)
                # packsite: region-local — stacking per-layer rows of one
                # replica's serve forward along a NEW layer axis; all
                # operands share one (replicated) sharding.
                new_kv = (jnp.stack(ks), jnp.stack(vs))
            else:
                for i in range(cfg.n_layers):
                    x, _ = block_cls(cfg, name=f"layer_{i}")(x, positions)
        x = RMSNorm(cfg.norm_eps, name="final_norm")(x)
        if cfg.xent_chunk:
            # Fused head+loss: the kernel is hoisted to this scope (param
            # path "lm_head_kernel" instead of "lm_head/kernel") and the
            # row-chunked CE never materializes full logits. Without
            # targets (init / inference) it degrades to a plain head.
            from tony_tpu.train import chunked_next_token_xent
            w = self.param("lm_head_kernel", nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "vocab")),
                (cfg.dim, cfg.vocab), jnp.float32)
            if targets is not None:
                return chunked_next_token_xent(x, w, targets,
                                               cfg.xent_chunk, cfg.dtype)
            logits = (x @ w.astype(cfg.dtype)).astype(jnp.float32)
            if kv is not None:
                return logits, new_kv
            return logits
        # lm_head matmul in bf16 (an f32 matmul runs at a fraction of MXU
        # bf16 peak and this is ~2·dim·vocab FLOPs/token) — or int8 when
        # the "lm_head" quant lane is on; logits cast to f32 afterwards
        # for a stable softmax in the loss.
        logits = _proj_dense(cfg, "lm_head", cfg.vocab,
                             ("embed", "vocab"), "lm_head")(x)
        if kv is not None:
            return logits.astype(jnp.float32), new_kv
        return logits.astype(jnp.float32)


@register("llama2-7b")
def llama2_7b(**kw) -> Transformer:
    return Transformer(TransformerConfig(**kw))


@register("llama-tiny")
def llama_tiny(**kw) -> Transformer:
    """Test-scale config: same code path as 7B at toy shapes."""
    defaults = dict(vocab=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                    ffn_hidden=128, max_seq=64, attention="reference",
                    scan_layers=True, remat=False)
    defaults.update(kw)
    return Transformer(TransformerConfig(**defaults))


@register("mixtral-8x7b")
def mixtral_8x7b(**kw) -> Transformer:
    """Mixtral-style sparse MoE: 8 experts, top-2 routing, GQA."""
    defaults = dict(vocab=32000, dim=4096, n_layers=32, n_heads=32,
                    n_kv_heads=8, ffn_hidden=14336, max_seq=4096,
                    moe_experts=8, moe_top_k=2)
    defaults.update(kw)
    return Transformer(TransformerConfig(**defaults))


@register("llama-moe-tiny")
def llama_moe_tiny(**kw) -> Transformer:
    """Test-scale MoE config: the mixtral code path at toy shapes."""
    defaults = dict(vocab=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                    ffn_hidden=128, max_seq=64, attention="reference",
                    scan_layers=True, remat=False, moe_experts=4,
                    moe_top_k=2)
    defaults.update(kw)
    return Transformer(TransformerConfig(**defaults))

