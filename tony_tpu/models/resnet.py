"""ResNet v1.5 for the ImageNet data-parallel north star (SURVEY.md §6:
ResNet-50 DP ≥55% MFU on a pod slice via ``tony submit``).

TPU-first choices: NHWC layout (XLA's native conv layout on TPU), bf16
compute with f32 params and f32 batch-norm statistics, and no
data-dependent control flow — the whole forward is one traced graph. Under
``jit`` over a dp/fsdp mesh the batch dim is sharded by
:func:`tony_tpu.parallel.batch_sharding`; BatchNorm's batch-mean then spans
the *global* batch because arrays are logically global (GSPMD inserts the
cross-device mean), matching synchronized-BN semantics without any NCCL-style
explicit allreduce.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tony_tpu.models import register

ModuleDef = Any


class FusedBNAct(nn.Module):
    """BatchNorm(+residual-add)(+ReLU) on the fused pallas kernels
    (:mod:`tony_tpu.ops.batchnorm` — VERDICT r3 #1: the BN reductions are
    51.3% of the ResNet step; this folds the whole epilogue into minimal
    HBM passes). Param/stat names match ``nn.BatchNorm`` (scale/bias,
    batch_stats mean/var). Falls back to plain XLA math when the shape
    has no clean tiling, and for eval (running stats: one elementwise
    pass XLA already fuses well)."""
    relu: bool = True
    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    scale_init: Any = nn.initializers.ones
    dtype: Any = jnp.bfloat16   # compute dtype for the non-kernel paths
    interpret: bool = False     # CPU tests run the kernels interpreted

    @nn.compact
    def __call__(self, x, residual: Optional[jax.Array] = None):
        from tony_tpu.ops.batchnorm import fused_bn_act

        c = x.shape[-1]
        gamma = self.param("scale", self.scale_init, (c,), jnp.float32)
        beta = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda *_: jnp.zeros((c,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda *_: jnp.ones((c,), jnp.float32))
        fused = None
        if not self.use_running_average:
            fused = fused_bn_act(x, gamma, beta, residual,
                                 eps=self.epsilon, relu=self.relu,
                                 interpret=self.interpret)
        if fused is not None:
            out, mean, var = fused
        else:
            if self.use_running_average:
                mean, var = ra_mean.value, ra_var.value
            else:  # XLA fallback for un-tileable shapes
                xf = x.astype(jnp.float32)
                axes = tuple(range(x.ndim - 1))
                mean = jnp.mean(xf, axis=axes)
                var = jnp.maximum(
                    jnp.mean(xf * xf, axis=axes) - mean * mean, 0.0)
            # Elementwise math in the compute dtype (like nn.BatchNorm
            # with dtype=bf16): an f32 path would bounce every activation
            # bf16→f32→bf16 — doubled HBM traffic on a bandwidth-bound
            # model. Only the [C]-vector prep stays f32.
            ct = self.dtype
            inv = (jax.lax.rsqrt(var + self.epsilon) * gamma)
            out = (x.astype(ct) - mean.astype(ct)) * inv.astype(ct) \
                + beta.astype(ct)
            if residual is not None:
                out = out + residual.astype(ct)
            if self.relu:
                out = jnp.maximum(out, 0.0)
            out = out.astype(x.dtype)
        if not self.use_running_average and not self.is_initializing() \
                and self.is_mutable_collection("batch_stats"):
            mom = self.momentum
            ra_mean.value = (mom * ra_mean.value
                             + (1 - mom) * jax.lax.stop_gradient(mean))
            ra_var.value = (mom * ra_var.value
                            + (1 - mom) * jax.lax.stop_gradient(var))
        return out


class Bottleneck(nn.Module):
    """1x1 → 3x3 → 1x1 bottleneck with projection shortcut (v1.5: the
    stride sits on the 3x3, not the 1x1)."""
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = nn.relu(self.norm()(y))
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = nn.relu(self.norm()(y))
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="proj")(residual)
            residual = self.norm(name="proj_bn")(residual)
        return nn.relu(y + residual)


class FusedBottleneck(nn.Module):
    """Bottleneck over the fused BN kernels: BN+ReLU epilogues are single
    kernels, and the block exit (zeros-init BN + residual add + ReLU) is
    ONE fused pass instead of three XLA fusions."""
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef    # partial(FusedBNAct, ...)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        if residual.shape[-1] != self.filters * 4 \
                or self.strides != (1, 1):
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="proj")(residual)
            residual = self.norm(relu=False, name="proj_bn")(residual)
        return self.norm(scale_init=nn.initializers.zeros)(
            y, residual=residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16      # compute dtype; params stay f32
    fused_bn: bool = False         # pallas BN+add+ReLU epilogues
    bn_interpret: bool = False     # interpret pallas kernels (CPU tests)
    # MLPerf-standard space-to-depth stem: the 7x7/s2 conv on 224²x3
    # becomes the mathematically equivalent 4x4/s1 conv on the s2d-packed
    # 112²x12 input (kernel zero-padded 7→8 taps; see s2d_stem_kernel and
    # tests/test_models.py::test_s2d_stem_equivalence). Input channels 3
    # pay a physically padded layout on TPU; 12 is no better per element
    # but touches the big tensor with 4x fewer rows — measured ~0.5 ms/step
    # (exp/s2d_results.txt).
    s2d_stem: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        # bf16 compute dtype: activations stay 2-byte through the norm
        # (f32 norms would bounce every activation bf16->f32->bf16, doubling
        # HBM traffic on a bandwidth-bound model); running stats and
        # scale/bias params remain f32 via param_dtype.
        if self.fused_bn:
            norm = partial(FusedBNAct, use_running_average=not train,
                           momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                           interpret=self.bn_interpret)
            block_cls = FusedBottleneck
        else:
            norm = partial(nn.BatchNorm, use_running_average=not train,
                           momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                           param_dtype=jnp.float32)
            block_cls = Bottleneck
        x = x.astype(self.dtype)
        if self.s2d_stem:
            n, h, w, c = x.shape
            x = x.reshape(n, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2,
                                                      4 * c)
            # Output position i consumes original rows 2i-3..2i+3 = packed
            # block rows i-2..i+1 → 4 taps, pad (2,1). Exact 7x7/s2
            # equivalence: the zero tap (original offset -4) multiplies
            # rows the 7x7 never read.
            x = conv(self.width, (4, 4), (1, 1),
                     padding=[(2, 1), (2, 1)], name="stem")(x)
        else:
            x = conv(self.width, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                     name="stem")(x)
        if self.fused_bn:
            x = norm(name="stem_bn")(x)
        else:
            x = nn.relu(norm(name="stem_bn")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, size in enumerate(self.stage_sizes):
            for block in range(size):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                x = block_cls(self.width * 2 ** stage, strides,
                              conv=conv, norm=norm)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x)
        return x


@register("resnet50")
def resnet50(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), **kw)


@register("resnet18-thin")
def resnet18_thin(**kw) -> ResNet:
    """Small variant for tests: same code path, toy width/depth."""
    kw.setdefault("width", 8)
    kw.setdefault("num_classes", 10)
    return ResNet(stage_sizes=(1, 1), **kw)


def s2d_stem_kernel(k7: jax.Array) -> jax.Array:
    """Transport a [7,7,Cin,Cout] stem kernel to the equivalent [4,4,4*Cin,
    Cout] space-to-depth kernel: packed tap (p,q,dr,dc) reads original tap
    (2p-1+dr, 2q-1+dc); the out-of-range taps (p=0,dr=0 → row -1) are the
    zero padding that makes 7→8 taps exact. Proof of equivalence:
    tests/test_models.py::test_s2d_stem_equivalence."""
    cin, cout = k7.shape[2], k7.shape[3]
    k8 = jnp.zeros((8, 8, cin, cout), k7.dtype).at[1:, 1:].set(k7)
    # (a, b) = (2p-1+dr, 2q-1+dc) → k8 index (a+1, b+1) = (2p+dr, 2q+dc).
    k4 = k8.reshape(4, 2, 4, 2, cin, cout)          # (p, dr, q, dc, ...)
    k4 = k4.transpose(0, 2, 1, 3, 4, 5)             # (p, q, dr, dc, ...)
    return k4.reshape(4, 4, 4 * cin, cout)


def resnet50_flops(batch: int, image: int = 224) -> int:
    """Analytic forward FLOPs (≈4.1 GFLOP @224²); training ≈3× forward.
    Used by bench.py's MFU computation."""
    # Standard figure: 4.089e9 MACs*2 fwd for 224x224.
    per_image = 8.2e9 * (image / 224) ** 2
    return int(per_image * batch)
