"""MNIST nets: the stand-ins for the reference's example workloads
(``tony-examples/mnist-tensorflow``, ``mnist-pytorch`` — SURVEY.md §2.2),
used by ``examples/`` and the distributed-training e2e tests."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from tony_tpu.models import register


class MLP(nn.Module):
    hidden: int = 512
    classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden)(x))
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(self.classes)(x)


class CNN(nn.Module):
    classes: int = 10

    @nn.compact
    def __call__(self, x):
        if x.ndim == 2:  # flat 784 → NHWC
            x = x.reshape((x.shape[0], 28, 28, 1))
        x = nn.relu(nn.Conv(32, (3, 3))(x))
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3))(x))
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(256)(x))
        return nn.Dense(self.classes)(x)


@register("mnist-mlp")
def mnist_mlp(**kw) -> MLP:
    return MLP(**kw)


@register("mnist-cnn")
def mnist_cnn(**kw) -> CNN:
    return CNN(**kw)
