"""MNIST nets: the stand-ins for the reference's example workloads
(``tony-examples/mnist-tensorflow``, ``mnist-pytorch`` — SURVEY.md §2.2),
used by ``examples/`` and the distributed-training e2e tests."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from tony_tpu.models import register


class MLP(nn.Module):
    hidden: int = 512
    classes: int = 10
    # Quantized compute lane (tony_tpu.ops.quant): every Dense runs the
    # int8×int8→int32 matmul with f32 rescale instead of the f32 matmul
    # (same kernel+bias shapes per layer). This is the loss-pin gate's
    # small harness: tests/test_quant.py trains both lanes and holds the
    # curves together within the committed tolerance.
    quant: bool = False

    @nn.compact
    def __call__(self, x):
        if self.quant:
            from tony_tpu.ops.quant import QuantDense

            # Explicit nn.Dense-style names: the two lanes share ONE
            # param tree (Dense_i/kernel+bias), so a checkpoint trained
            # on either lane restores into the other.
            dense = lambda n, i: QuantDense(n, use_bias=True,
                                            name=f"Dense_{i}")
        else:
            dense = lambda n, i: nn.Dense(n, name=f"Dense_{i}")
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(dense(self.hidden, 0)(x))
        x = nn.relu(dense(self.hidden, 1)(x))
        return dense(self.classes, 2)(x)


class CNN(nn.Module):
    classes: int = 10

    @nn.compact
    def __call__(self, x):
        if x.ndim == 2:  # flat 784 → NHWC
            x = x.reshape((x.shape[0], 28, 28, 1))
        x = nn.relu(nn.Conv(32, (3, 3))(x))
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3))(x))
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(256)(x))
        return nn.Dense(self.classes)(x)


@register("mnist-mlp")
def mnist_mlp(**kw) -> MLP:
    return MLP(**kw)


@register("mnist-cnn")
def mnist_cnn(**kw) -> CNN:
    return CNN(**kw)
