"""TPU chip discovery: the scheduler-side resource census.

Mirrors ``com.linkedin.tony.util.gpu.GpuDiscoverer`` (upstream
``tony-core/src/main/java/com/linkedin/tony/util/gpu/``, unverified —
SURVEY.md §0/§2.1): the reference shells out to ``nvidia-smi -q -x`` and
parses XML so the AM can schedule/isolate GPUs pre-YARN-3.1. The TPU
equivalent needs no subprocess: chips appear as ``/dev/accel*`` (TPU-VM) or
``/dev/vfio/*`` device nodes, and the libtpu env describes the host's slice
topology. The count feeds the scheduler's ``total_tpus`` so over-subscribed
``tony.<jobtype>.tpus`` asks fail at launch like an RM rejecting an
unsatisfiable resource request.
"""

from __future__ import annotations

import glob
import os
import re
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class TpuTopology:
    num_chips: int
    source: str          # devfs | env | jax | none


def _chips_from_devfs() -> Optional[int]:
    accels = glob.glob("/dev/accel*")
    if accels:
        return len(accels)
    vfio = [p for p in glob.glob("/dev/vfio/*") if p != "/dev/vfio/vfio"]
    if vfio:
        return len(vfio)
    return None


def _chips_from_env(env=os.environ) -> Optional[int]:
    bounds = env.get("TPU_CHIPS_PER_HOST_BOUNDS")  # e.g. "2,2,1"
    if bounds:
        dims = [int(x) for x in re.findall(r"\d+", bounds)]
        if dims:
            n = 1
            for d in dims:
                n *= d
            return n
    visible = env.get("TPU_VISIBLE_DEVICES")
    if visible:
        return len([c for c in visible.split(",") if c.strip() != ""])
    return None


def discover_tpus(use_jax: bool = False) -> TpuTopology:
    """Count this host's TPU chips. Order: device nodes, libtpu env, then
    (opt-in — importing jax initializes the runtime) jax itself."""
    n = _chips_from_devfs()
    if n is not None:
        return TpuTopology(n, "devfs")
    n = _chips_from_env()
    if n is not None:
        return TpuTopology(n, "env")
    if use_jax:
        try:
            import jax
            devs = [d for d in jax.local_devices()
                    if d.platform not in ("cpu",)]
            if devs:
                return TpuTopology(len(devs), "jax")
        except Exception:
            pass
    return TpuTopology(0, "none")
