"""Online serving plane: the framework's second workload class.

Training gave TonY-TPU an owned compute/checkpoint/data plane; this
package opens the inference loop the same way — owned end to end, per
TF-Replicator's lesson (PAPERS 1902.00465: a framework that doesn't own
the execution loop watches every user rebuild it badly):

* :mod:`~tony_tpu.serve.kvcache` — the paged KV cache: a fixed-size
  block pool with per-sequence block tables; admission failures are a
  typed :class:`~tony_tpu.serve.kvcache.AdmissionError`, never an OOM;
* :mod:`~tony_tpu.serve.engine` — the continuous-batching loop:
  admission queue (the data plane's prefetcher pattern in reverse —
  work queued ahead of the consumer instead of staged ahead of it),
  bucketed static shapes so requests join and leave the running batch
  at iteration granularity without recompilation, and a flash-decoding
  attention step (:func:`tony_tpu.ops.flash_decode`) over the paged
  cache;
* :mod:`~tony_tpu.serve.replica` — one serving replica: sharded
  training checkpoints load through elastic restore onto the replica's
  own mesh (f32 master → bf16 serving via the restore-time dtype
  policy), requests arrive over the control-plane RPC wire (fronted by
  the existing TCP proxy), and qps/p99/queue-depth ride the executor
  heartbeat so the AM can scale replicas against load;
* :mod:`~tony_tpu.serve.scaling` — the pure (jax-free) replica-scaling
  policy the AM's monitor loop applies;
* :mod:`~tony_tpu.serve.spec` — the speculative decoding lane: a draft
  lane (second small model, or the self-drafting n-gram fallback)
  proposes k tokens and the target verifies all k+1 positions in ONE
  forward through the same ``q_block`` row-block step the decode loop
  runs — greedy-path token streams and logits stay BITWISE identical to
  the non-speculative engine while tokens-per-forward multiplies;
* :mod:`~tony_tpu.serve.prefix` — block-level chain hashing (jax-free):
  the content-address scheme the pool's prefix tier and the router's
  overlap scoring share, so a replica and the gateway derive identical
  keys from identical tokens;
* :mod:`~tony_tpu.serve.router` — the cross-replica request router
  (jax-free): scores the elastic replica set by prefix-cache overlap
  (block digests carried on the heartbeat), queue depth, and p99, with
  sticky session affinity for multi-turn traffic and failover
  re-dispatch on replica retirement — the fleet, not a replica, is the
  unit of throughput;
* :mod:`~tony_tpu.serve.disagg` — disaggregated prefill/decode
  (jax-free): prefill and decode split onto separate replica roles
  (heterogeneous gangs of one job) with KV-block handoff over the RPC
  wire — per-block CRC, shared-prefix stems adopted instead of
  re-transferred, bounded retry with a typed :class:`~tony_tpu.serve.
  disagg.HandoffError`, and the decode replica's loop issuing zero
  prefill launches while the prefill gang absorbs bursts;
* :mod:`~tony_tpu.serve.kvstore` — the persistent prefix store
  (jax-free): hot published stems on disk through the ckpt plane's
  stage-and-rename commit, keyed by chain hash, so a fresh replica or
  scale-up grant warms its prefix tier from the store instead of
  recompute. Together with the pool's host-offload tier and
  conversation parking (:mod:`~tony_tpu.serve.kvcache` /
  :mod:`~tony_tpu.serve.engine`) this completes the KV memory
  hierarchy: device pool → pinned host RAM → disk.

Numerics contract: continuous-batching decode is BIT-identical to a
sequential full prefill of the same tokens — every op in the serve
forward is row-independent and all row counts stay at sublane-tile
multiples (the engine's ``q_block`` row blocks), so joining a batch or
riding the paged cache cannot change a single bit of any request's
logits. ``tests/test_serve.py`` pins this end to end.
"""

from typing import Any

__all__ = ["AdmissionError", "Completion", "DecodeFront", "EngineFront",
           "HandoffError", "KVShipper", "ModelDraft", "NgramDraft",
           "NoReplicaError", "PagedKVCache", "PrefillFront",
           "PrefixStore", "Request", "RequestRouter", "RouterPolicy",
           "RouterServer", "ServeEngine", "SpecEngine", "disagg",
           "engine", "kvcache", "kvstore", "prefix", "replica",
           "router", "scaling", "spec"]

# LAZY facade (PEP 562, like tony_tpu.analysis): the engine pulls jax,
# but the AM's autoscaler only needs the pure scaling policy and the
# executor's heartbeat reader needs nothing here at all — the control
# plane must be able to import serve submodules without paying (or
# breaking on) a jax import. name -> owning submodule (None = the name
# IS a submodule).
_LAZY = {
    "AdmissionError": "kvcache", "PagedKVCache": "kvcache",
    "Completion": "engine", "Request": "engine", "ServeEngine": "engine",
    "EngineFront": "engine",
    "ModelDraft": "spec", "NgramDraft": "spec", "SpecEngine": "spec",
    "NoReplicaError": "router", "RequestRouter": "router",
    "RouterPolicy": "router", "RouterServer": "router",
    "HandoffError": "disagg", "KVShipper": "disagg",
    "PrefillFront": "disagg", "DecodeFront": "disagg",
    "PrefixStore": "kvstore",
    "disagg": None,
    "engine": None, "kvcache": None, "kvstore": None, "prefix": None,
    "replica": None, "router": None, "scaling": None, "spec": None,
}


def __getattr__(name: str) -> Any:
    import importlib

    owner = _LAZY.get(name, "<missing>")
    if owner == "<missing>":
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    if owner is None:
        return importlib.import_module(f"{__name__}.{name}")
    return getattr(importlib.import_module(f"{__name__}.{owner}"), name)


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
