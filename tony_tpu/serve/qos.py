"""Per-tenant QoS classes for the serving engine (jax-free).

Requests carry a ``tenant`` tag end-to-end (CLI → router → engine →
heartbeat); this module turns the ``tony.serve.qos.tenants`` CSV into a
weighted-fair KV-block budget that the engine consults at admission
time. The mechanism is deliberately thin:

* The **policy** is a frozen weight map ("gold:3,silver:1"). A tenant's
  budget over an ``n_blocks`` pool is its weight's share of the weights
  of the tenants *currently active* (holding blocks or queued) — work
  conserving: a lone tenant gets the whole pool, and an idle tenant's
  share redistributes instead of sitting reserved.
* The **enforcement point** is the engine's admission scan, not the
  paged pool: the pool's refcount/free/LRU partition stays untouched,
  the engine simply defers a request whose tenant is over budget and
  lets later tenants' requests admit past it (per-tenant FIFO is
  preserved — a deferred tenant's LATER requests also wait).
* **Back-pressure** is the existing typed ``AdmissionError``: a tenant
  whose queue exceeds ``max_queue`` (0 = unbounded) is rejected
  retryable at submit, never silently dropped — and never the victim
  tenant, whose stream stays bitwise identical to an unloaded engine.

Untagged requests bypass budgets entirely, and with no policy armed the
admission path is byte-identical to an engine without QoS.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, Optional

__all__ = ["QosPolicy", "parse_tenants"]


def parse_tenants(spec: str) -> Dict[str, float]:
    """Parse the ``tony.serve.qos.tenants`` CSV: ``"gold:3,silver:1"``
    → ``{"gold": 3.0, "silver": 1.0}``. A bare name gets weight 1.
    Raises ``ValueError`` (at submit time, via the CLI) on empty names,
    non-positive or non-numeric weights, and duplicate tenants."""
    classes: Dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"empty tenant name in qos spec {spec!r}")
        if name in classes:
            raise ValueError(f"duplicate tenant {name!r} in qos spec")
        try:
            weight = float(w) if w.strip() else 1.0
        except ValueError:
            raise ValueError(
                f"tenant {name!r}: weight {w!r} is not a number") from None
        if weight <= 0 or weight != weight:  # reject <=0 and NaN
            raise ValueError(
                f"tenant {name!r}: weight must be > 0, got {w!r}")
        classes[name] = weight
    if not classes:
        raise ValueError(
            f"qos spec {spec!r} names no tenants (an empty spec means "
            f"QoS off — leave the conf key unset instead)")
    return classes


@dataclass(frozen=True)
class QosPolicy:
    """Weighted-fair tenant classes over a paged KV pool.

    ``classes`` maps tenant name → weight. Tenants *not* in the map are
    still admitted (the tag is advisory routing/metering metadata) at
    ``default_weight``; a policy therefore never turns a valid request
    away for being unknown — only for being over budget or over its
    queue cap."""

    classes: Dict[str, float] = field(default_factory=dict)
    default_weight: float = 1.0
    # Per-tenant pending-queue cap enforced at submit (0 = unbounded).
    max_queue: int = 0

    def __post_init__(self) -> None:
        for name, w in self.classes.items():
            if not name or w <= 0:
                raise ValueError(
                    f"qos class {name!r}: weight must be > 0, got {w}")
        if self.default_weight <= 0:
            raise ValueError("default_weight must be > 0")
        if self.max_queue < 0:
            raise ValueError("max_queue must be >= 0")

    @classmethod
    def from_conf(cls, conf) -> Optional["QosPolicy"]:
        """Build from ``tony.serve.qos.*`` conf keys; None when the
        tenants CSV is empty/absent (the byte-identical untagged path)."""
        from tony_tpu import conf as conf_mod
        spec = conf.get(conf_mod.SERVE_QOS_TENANTS) or ""
        if not spec.strip():
            return None
        return cls(classes=parse_tenants(spec),
                   max_queue=conf.get_int(conf_mod.SERVE_QOS_MAX_QUEUE, 0))

    def weight(self, tenant: str) -> float:
        return self.classes.get(tenant, self.default_weight)

    def budget(self, tenant: str, n_blocks: int,
               active: Iterable[str]) -> int:
        """Fair-share block budget for ``tenant`` over an ``n_blocks``
        pool, given the set of *active* tenants (holding blocks or
        queued — include ``tenant`` itself). Work-conserving: the
        denominator is the active weights only, so a lone tenant's
        budget is the whole pool and shares renormalize as tenants come
        and go. Floor of one block so a positive-weight tenant can
        always make progress once the pool drains."""
        names = set(active)
        names.add(tenant)
        total = sum(self.weight(n) for n in names)
        if total <= 0:
            return n_blocks
        return max(1, int(n_blocks * self.weight(tenant) / total))
