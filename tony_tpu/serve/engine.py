"""Continuous-batching inference engine over the paged KV cache.

One engine owns one replica's decode loop. The structure inverts the
data plane's prefetcher (PR 4): there, a producer thread stages batches
AHEAD of the training step; here, callers queue requests BEHIND the
decode loop (the admission queue) and the loop pulls them into the
running batch at iteration granularity — a request joins as soon as pool
blocks and a batch slot are free, and leaves (eviction) the step its
generation completes, with every other sequence's decode undisturbed.

Static shapes are bucketed so join/evict never recompiles:

* **row blocks** — every forward processes query rows in blocks of
  ``q_block`` (default 16, the bf16 sublane tile): prefill pads the
  prompt to a whole number of blocks, decode processes one block per
  sequence (1 real new token + padding rows whose cache writes are
  dropped). Fixed-tile row counts are ALSO the numerics contract: every
  serve op is row-independent at tile-multiple shapes, which is what
  makes continuous-batching decode bit-identical to a sequential full
  prefill of the same tokens (the tests pin it; single-row GEMV paths
  are where XLA CPU breaks row invariance, so the engine never issues
  one);
* **decode buckets** — the joined batch pads up to the next bucket size,
  so the decode step compiles once per bucket, not per batch
  composition;
* **one context extent** — the KV buffer gathered per step is always
  ``ctx_pad = nb_max · block_size`` positions, so ragged sequence
  lengths never change a shape (masking by absolute position does the
  rest).

The decode step is registered with the collective planner at build time
(:func:`tony_tpu.profiler.record_collective`, plane ``serve_decode``)
with an EMPTY expected set: a replica's decode touches no inter-chip
collective — its mesh exists for memory, not for cross-replica math —
and ``tony analyze --config serve`` audits the traced step against that
promise (a GSPMD-inserted reshard is a finding, not a slowdown).
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu._trace import trace_record
from tony_tpu.compat import mesh_context
from tony_tpu.serve.kvcache import AdmissionError, PagedKVCache

_record = functools.partial(trace_record, "serve")


@dataclasses.dataclass
class Request:
    """One generation request. ``max_new_tokens`` is a hard cap; the
    engine reserves pool blocks for ``len(tokens) + max_new_tokens`` at
    admission so decode can never exhaust the pool mid-flight."""
    rid: Any
    tokens: List[int]
    max_new_tokens: int


@dataclasses.dataclass
class Completion:
    """One finished request: the generated tokens, per-position f32
    logits when the engine keeps them (``keep_logits=True`` — the test
    pin surface), and the request's wall latency."""
    rid: Any
    prompt: List[int]
    tokens: List[int]
    logits: Optional[List[np.ndarray]]
    latency_s: float


class _Seq:
    __slots__ = ("rid", "tokens", "n_prompt", "remaining", "logits",
                 "t_submit")

    def __init__(self, req: Request, t_submit: float):
        self.rid = req.rid
        self.tokens: List[int] = list(req.tokens)
        self.n_prompt = len(req.tokens)
        self.remaining = int(req.max_new_tokens)
        self.logits: List[np.ndarray] = []
        self.t_submit = t_submit


def _bucket_of(buckets: Sequence[int], n: int) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"batch {n} exceeds the largest decode bucket "
                     f"{max(buckets)}")


def build_step_fn(model: Any, *, n_layers: int, n_blocks: int,
                  block_size: int, kv_dim: int, ctx_pad: int, b: int,
                  t: int) -> Callable:
    """The (b, t)-shaped jitted serve step over a paged pool: gather
    each sequence's blocks into the fixed-extent KV buffers, run the
    serve forward, commit the fresh rows back to the pool through the
    host-computed flat scatter indices (OOB rows drop). Pools are
    donated — callers immediately rebind them, so the update is
    in-place-ish.

    Module-level so the speculative lane's draft model
    (:mod:`tony_tpu.serve.spec`) runs the IDENTICAL program over its own
    pool: one builder, one jaxpr shape family, one signature pin."""
    L, nb, bs, kvd, ctx = n_layers, n_blocks, block_size, kv_dim, ctx_pad

    def fn(params, pool_k, pool_v, tokens, positions, tables,
           flat_idx):
        # mode="clip", NOT the default NaN-fill: table padding (and
        # the scratch reference's contiguous table on a small pool)
        # may point past the pool, and those positions are masked by
        # the attention — but only 0 x FINITE is exactly 0; a
        # NaN-filled block would poison every masked row.
        kbuf = jnp.take(pool_k, tables, axis=1,
                        mode="clip").reshape(L, b, ctx, kvd)
        vbuf = jnp.take(pool_v, tables, axis=1,
                        mode="clip").reshape(L, b, ctx, kvd)
        logits, (knew, vnew) = model.apply(
            {"params": params}, tokens, positions=positions,
            kv=(kbuf, vbuf))
        pk = pool_k.reshape(L, nb * bs, kvd).at[:, flat_idx].set(
            knew.astype(pool_k.dtype), mode="drop")
        pv = pool_v.reshape(L, nb * bs, kvd).at[:, flat_idx].set(
            vnew.astype(pool_v.dtype), mode="drop")
        return (logits, pk.reshape(L, nb, bs, kvd),
                pv.reshape(L, nb, bs, kvd))

    return jax.jit(fn, donate_argnums=(1, 2))


class PagedModelRunner:
    """Shared geometry + jitted-step plumbing over ONE model and ONE
    paged KV pool: the base of both the serve engine and the
    speculative lane's draft model (:class:`tony_tpu.serve.spec.
    ModelDraft`). Owning it here keeps the two lanes on one jit cache
    shape, one mesh/donation discipline, and one forward counter idiom —
    a change to how a step runs cannot drift between them."""

    def _init_paged(self, model: Any, params: Any, *, ctx_max: int,
                    block_size: int, q_block: int,
                    decode_buckets: Sequence[int], max_running: int,
                    n_blocks: Optional[int], mesh: Optional[Any]) -> None:
        cfg = model.cfg
        if q_block % 8:
            raise ValueError(f"q_block must be a sublane-tile multiple "
                             f"(8), got {q_block}")
        self.model = model
        self.params = params
        self.mesh = mesh
        self.q_block = int(q_block)
        self.decode_buckets = tuple(sorted(set(
            list(decode_buckets) + [max_running])))
        self.max_running = int(max_running)
        self.n_layers = cfg.n_layers
        self.kv_dim = cfg.n_kv_heads * cfg.head_dim
        self.block_size = int(block_size)
        nb_max = -(-int(ctx_max) // self.block_size)
        self.nb_max = nb_max
        self.ctx_pad = nb_max * self.block_size
        if n_blocks is None:
            n_blocks = nb_max * self.max_running
        self.cache = PagedKVCache(self.n_layers, self.kv_dim,
                                  n_blocks=n_blocks,
                                  block_size=self.block_size,
                                  dtype=cfg.dtype)
        self._fns: Dict[Tuple[int, int], Callable] = {}
        # Forward-launch counter (prefills + decode/verify steps): the
        # machine-independent cost of a schedule — on an accelerator the
        # forward dominates wall time, so fewer launches for the same
        # tokens IS the batching/speculation win.
        self.forwards = 0

    def _fn(self, b: int, t: int) -> Callable:
        """The cached view of :func:`build_step_fn` — prefill, decode,
        AND the speculative lane's k+1-row verification all share these
        entries (verification is a decode-shaped launch with more real
        rows, so it adds zero compiles)."""
        key = (b, t)
        if key not in self._fns:
            self._fns[key] = build_step_fn(
                self.model, n_layers=self.n_layers,
                n_blocks=self.cache.n_blocks, block_size=self.block_size,
                kv_dim=self.kv_dim, ctx_pad=self.ctx_pad, b=b, t=t)
        return self._fns[key]

    def _run_fn(self, b, t, tokens, positions, tables, flat_idx):
        fn = self._fn(b, t)
        args = (self.params, self.cache.k, self.cache.v,
                jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(tables), jnp.asarray(flat_idx))
        if self.mesh is not None:
            with mesh_context(self.mesh):
                logits, pk, pv = fn(*args)
        else:
            logits, pk, pv = fn(*args)
        self.cache.k, self.cache.v = pk, pv
        self.forwards += 1
        return logits


class ServeEngine(PagedModelRunner):
    """Continuous-batching loop for one replica.

    ``model`` is a serve-capable flax module (today:
    :class:`tony_tpu.models.transformer.Transformer` — its ``kv=``
    forward); ``params`` its (restored, typically bf16) param tree.
    ``mesh`` wraps every jitted call in the replica's mesh context so
    sharded params compute in place; ``None`` runs on the default
    device placement.
    """

    def __init__(self, model: Any, params: Any, *, ctx_max: int,
                 block_size: int = 16, n_blocks: Optional[int] = None,
                 q_block: int = 16, decode_buckets: Sequence[int] = (4, 16),
                 max_running: int = 16, mesh: Optional[Any] = None,
                 keep_logits: bool = False, join_policy: str = "continuous",
                 stats_window_s: float = 60.0, tag: str = "serve"):
        if join_policy not in ("continuous", "static"):
            raise ValueError(f"unknown join_policy {join_policy!r} "
                             "(continuous|static)")
        self._init_paged(model, params, ctx_max=ctx_max,
                         block_size=block_size, q_block=q_block,
                         decode_buckets=decode_buckets,
                         max_running=max_running, n_blocks=n_blocks,
                         mesh=mesh)
        self.keep_logits = keep_logits
        self.join_policy = join_policy
        self.tag = tag
        self._queue: deque = deque()
        self._lock = threading.Lock()
        self._running: List[_Seq] = []
        # Telemetry: completion ring for p50/p99, monotonic counters for
        # rates — O(1) per step, million-request safe.
        # (t_done, latency_s, n_tokens) per completion: rates and
        # percentiles are computed over a TIME window, not lifetime —
        # the autoscaler reads p99/qps as "now", and a latency spike
        # from an hour-old burst must age out or scale-down never fires.
        self._events: deque = deque(maxlen=512)
        self.stats_window_s = float(stats_window_s)
        self._completed = 0
        self._tokens_out = 0           # tokens of COMPLETED requests
        self._emitted = 0              # every generated token, at emit
        self._t0 = time.monotonic()
        self._steps = 0
        self.register_plan()

    # -- planner/profiler registration ------------------------------------
    def register_plan(self) -> None:
        """Register the decode step's (empty) collective schedule with
        the unified planner record plus the engine geometry — the
        day-one registration ROADMAP asks of every new step-path plane;
        ``tony analyze --config serve`` audits the traced decode against
        exactly this promise."""
        trace_record("collective", "serve_decode", kind="none",
                     plane="serve_decode", axes=[], nbytes=[],
                     note="replica-local decode: zero inter-chip "
                          "collectives")
        _record(self.tag, ctx_pad=self.ctx_pad,
                block_size=self.block_size, nb_max=self.nb_max,
                n_blocks=self.cache.n_blocks, q_block=self.q_block,
                decode_buckets=list(self.decode_buckets),
                max_running=self.max_running,
                join_policy=self.join_policy)

    def expected_collectives(self) -> list:
        """The planner-registered expected collective set of the decode
        step: empty — a replica mesh shards memory, never the decode
        math. The analyzer reconciles the traced program against this."""
        return []

    # -- admission ---------------------------------------------------------
    def submit(self, req: Request) -> None:
        """Queue a request (thread-safe). Requests that can NEVER fit
        the context buffer are rejected now with a non-retryable
        :class:`AdmissionError`; pool pressure is handled later, at
        join time, by leaving the request queued."""
        total = len(req.tokens) + req.max_new_tokens
        if not req.tokens:
            raise ValueError(f"request {req.rid!r}: empty prompt")
        needed = self.cache.blocks_for(total)
        if total > self.ctx_pad or needed > self.cache.n_blocks:
            # Over the context extent OR over the ENTIRE pool (an
            # explicit small n_blocks): queueing it as retryable would
            # livelock the loop — join would re-raise forever with
            # nothing ever freeing enough.
            raise AdmissionError(
                f"request {req.rid!r} needs {total} positions "
                f"({needed} blocks) > engine capacity (context "
                f"{self.ctx_pad}, pool {self.cache.n_blocks} blocks); "
                f"it can never be admitted",
                needed_blocks=needed,
                free_blocks=self.cache.free_blocks, retryable=False)
        with self._lock:
            self._queue.append((req, time.monotonic()))

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def running(self) -> int:
        return len(self._running)

    # -- prefill -----------------------------------------------------------
    def _prefill(self, seq: _Seq) -> None:
        t_real = len(seq.tokens)
        t_pad = -(-t_real // self.q_block) * self.q_block
        tokens = np.zeros((1, t_pad), np.int32)
        tokens[0, :t_real] = seq.tokens
        positions = np.broadcast_to(
            np.arange(t_pad, dtype=np.int32)[None], (1, t_pad)).copy()
        tables = self.cache.table_array([seq.rid], self.nb_max)
        flat = np.full((1, t_pad), self.cache.oob_index, np.int32)
        for p in range(t_real):
            flat[0, p] = self.cache.flat_index(seq.rid, p)
        logits = self._run_fn(1, t_pad, tokens, positions, tables, flat)
        last = np.asarray(logits[0, t_real - 1], np.float32)
        self._emit_token(seq, last)

    # -- decode ------------------------------------------------------------
    def _decode(self) -> None:
        seqs = list(self._running)
        b = _bucket_of(self.decode_buckets, len(seqs))
        t = self.q_block
        tokens = np.zeros((b, t), np.int32)
        positions = np.zeros((b, t), np.int32)
        tables = np.zeros((b, self.nb_max), np.int32)
        flat = np.full((b, t), self.cache.oob_index, np.int32)
        tables[:len(seqs)] = self.cache.table_array(
            [s.rid for s in seqs], self.nb_max)
        for i, s in enumerate(seqs):
            p0 = len(s.tokens) - 1          # the newest, not-yet-fed token
            tokens[i, 0] = s.tokens[-1]
            positions[i] = p0 + np.arange(t, dtype=np.int32)
            flat[i, 0] = self.cache.flat_index(s.rid, p0)
        logits = self._run_fn(b, t, tokens, positions, tables, flat)
        rows = np.asarray(logits[:len(seqs), 0], np.float32)
        for i, s in enumerate(seqs):
            self._emit_token(s, rows[i])

    def _emit_token(self, seq: _Seq, row: np.ndarray) -> None:
        if self.keep_logits:
            seq.logits.append(row.copy())
        seq.tokens.append(int(np.argmax(row)))   # greedy: deterministic
        seq.remaining -= 1
        self._emitted += 1

    # -- scheduling --------------------------------------------------------
    def _join(self, results: List[Completion]) -> None:
        if self.join_policy == "static" and self._running:
            return
        while len(self._running) < self.max_running:
            with self._lock:
                if not self._queue:
                    return
                req, t_submit = self._queue[0]
            try:
                self.cache.reserve(req.rid,
                                   len(req.tokens) + req.max_new_tokens)
            except AdmissionError:
                return                      # pool pressure: stay queued
            with self._lock:
                self._queue.popleft()
            seq = _Seq(req, t_submit)
            self._prefill(seq)
            if seq.remaining <= 0:          # max_new_tokens == 1
                self._evict(seq, results)
            else:
                self._running.append(seq)

    def _evict(self, seq: _Seq, results: List[Completion]) -> None:
        self.cache.free_seq(seq.rid)
        now = time.monotonic()
        self._events.append((now, now - seq.t_submit,
                             len(seq.tokens) - seq.n_prompt))
        self._completed += 1
        self._tokens_out += len(seq.tokens) - seq.n_prompt
        results.append(Completion(
            rid=seq.rid, prompt=seq.tokens[:seq.n_prompt],
            tokens=seq.tokens[seq.n_prompt:],
            logits=seq.logits if self.keep_logits else None,
            latency_s=now - seq.t_submit))

    def step(self) -> List[Completion]:
        """One engine iteration: join what fits, decode one token for
        every running sequence, evict what finished. Returns the
        completions this step produced."""
        results: List[Completion] = []
        self._join(results)
        if self._running:
            self._decode()
            still = []
            for s in self._running:
                if s.remaining <= 0:
                    self._evict(s, results)
                else:
                    still.append(s)
            self._running = still
        self._steps += 1
        return results

    def run(self, max_steps: Optional[int] = None) -> List[Completion]:
        """Drive :meth:`step` until queue and batch drain (or
        ``max_steps``)."""
        out: List[Completion] = []
        while (self.queue_depth or self._running) and \
                (max_steps is None or self._steps < max_steps):
            out.extend(self.step())
        return out

    # -- the sequential reference -----------------------------------------
    def full_prefill_logits(self, tokens: Sequence[int]) -> np.ndarray:
        """Sequential full-prefill reference: process ``tokens`` as ONE
        isolated prefill on a scratch pool (same jitted shape family,
        same ops) and return the real rows' f32 logits ``[len, vocab]``.
        The continuous-batching pin compares each request's streamed
        decode logits against rows of THIS, bit for bit."""
        t_real = len(tokens)
        if t_real > self.ctx_pad:
            raise ValueError(f"{t_real} tokens > engine context "
                             f"{self.ctx_pad}")
        t_pad = -(-t_real // self.q_block) * self.q_block
        toks = np.zeros((1, t_pad), np.int32)
        toks[0, :t_real] = list(tokens)
        positions = np.broadcast_to(
            np.arange(t_pad, dtype=np.int32)[None], (1, t_pad)).copy()
        # Contiguous scratch table on a zero pool of the SAME geometry,
        # so the jit cache is shared with live prefills (clipped: the
        # pool may hold fewer blocks than the context extent, and the
        # tail positions are masked anyway).
        tables = np.minimum(np.arange(self.nb_max, dtype=np.int32),
                            self.cache.n_blocks - 1)[None].copy()
        flat = np.full((1, t_pad), self.cache.oob_index, np.int32)
        bs = self.block_size
        for p in range(t_real):
            flat[0, p] = (p // bs) * bs + (p % bs)
        fn = self._fn(1, t_pad)
        scratch_k = jnp.zeros_like(self.cache.k)
        scratch_v = jnp.zeros_like(self.cache.v)
        args = (self.params, scratch_k, scratch_v, jnp.asarray(toks),
                jnp.asarray(positions), jnp.asarray(tables),
                jnp.asarray(flat))
        if self.mesh is not None:
            with mesh_context(self.mesh):
                logits, _, _ = fn(*args)
        else:
            logits, _, _ = fn(*args)
        return np.asarray(logits[0, :t_real], np.float32)

    # -- telemetry ---------------------------------------------------------
    def stats(self) -> Dict[str, float]:
        """The serve heartbeat triple (+ rates): qps, p50/p99 request
        latency, queue depth. Rates and percentiles cover the last
        ``stats_window_s`` only (bounded by engine age), so an idle
        replica's p99 decays to 0 and the autoscaler's scale-down gate
        can actually fire; ``completed``/``steps``/``forwards`` stay
        lifetime counters."""
        now = time.monotonic()
        recent = [(l, n) for t, l, n in self._events
                  if now - t <= self.stats_window_s]
        lat = sorted(l for l, _ in recent)
        dt = max(1e-9, min(self.stats_window_s, now - self._t0))

        def pct(p: float) -> float:
            if not lat:
                return 0.0
            return lat[min(len(lat) - 1, int(p * (len(lat) - 1) + 0.5))]

        stats = {
            "qps": len(recent) / dt,
            "tokens_per_s": sum(n for _, n in recent) / dt,
            "p50_ms": 1e3 * pct(0.50),
            "p99_ms": 1e3 * pct(0.99),
            "queue_depth": float(self.queue_depth),
            "running": float(len(self._running)),
            "completed": float(self._completed),
            "steps": float(self._steps),
            "forwards": float(self.forwards),
            # Effective throughput for the autoscaler: generated tokens
            # per TARGET forward launch (lifetime), counted at EMIT time
            # so a replica mid-way through long generations reports what
            # it is actually producing, not zero until first completion.
            # Raw forward counts undercount a speculative replica's real
            # throughput — ScalingPolicy's decision matrix is unchanged,
            # but the heartbeat now carries the honest number (the
            # speculative lane also reports its acceptance rate; 0.0
            # here).
            "tokens_per_forward": (self._emitted / self.forwards
                                   if self.forwards else 0.0),
            "acceptance_rate": 0.0,
        }
        stats.update(self._extra_stats())
        _record(f"{self.tag}_stats", **stats)
        return stats

    def _extra_stats(self) -> Dict[str, float]:
        """Subclass hook (tony_tpu.serve.spec overrides): extra fields
        merged into :meth:`stats` before it is recorded/published."""
        return {}

    def write_stats(self, path: str) -> None:
        """Atomically publish :meth:`stats` as JSON — the file the
        executor's heartbeat loop piggybacks to the AM (jax-free on the
        reader side)."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(self.stats(), fh)
        os.replace(tmp, path)

    # -- static-analysis hook ---------------------------------------------
    def decode_traced(self, batch: Optional[int] = None):
        """``(jitted, example_args)`` of the canonical decode bucket for
        :func:`tony_tpu.analysis.analyze_serve_step` — the same jit the
        loop runs, traced, never executed."""
        b = _bucket_of(self.decode_buckets,
                       batch if batch is not None else 1)
        t = self.q_block
        args = (self.params, self.cache.k, self.cache.v,
                jnp.zeros((b, t), jnp.int32),
                jnp.zeros((b, t), jnp.int32),
                jnp.zeros((b, self.nb_max), jnp.int32),
                jnp.full((b, t), self.cache.oob_index, jnp.int32))
        return self._fn(b, t), args
