"""Continuous-batching inference engine over the paged KV cache.

One engine owns one replica's decode loop. The structure inverts the
data plane's prefetcher (PR 4): there, a producer thread stages batches
AHEAD of the training step; here, callers queue requests BEHIND the
decode loop (the admission queue) and the loop pulls them into the
running batch at iteration granularity — a request joins as soon as pool
blocks and a batch slot are free, and leaves (eviction) the step its
generation completes, with every other sequence's decode undisturbed.

Static shapes are bucketed so join/evict never recompiles:

* **row blocks** — every forward processes query rows in blocks of
  ``q_block`` (default 16, the bf16 sublane tile): prefill pads the
  prompt to a whole number of blocks, decode processes one block per
  sequence (1 real new token + padding rows whose cache writes are
  dropped). Fixed-tile row counts are ALSO the numerics contract: every
  serve op is row-independent at tile-multiple shapes, which is what
  makes continuous-batching decode bit-identical to a sequential full
  prefill of the same tokens (the tests pin it; single-row GEMV paths
  are where XLA CPU breaks row invariance, so the engine never issues
  one);
* **decode buckets** — the joined batch pads up to the next bucket size,
  so the decode step compiles once per bucket, not per batch
  composition;
* **one context extent** — the KV buffer gathered per step is always
  ``ctx_pad = nb_max · block_size`` positions, so ragged sequence
  lengths never change a shape (masking by absolute position does the
  rest).

Two admission-path features ride those shapes since PR 13, both OFF by
default so the unrouted engine is byte-for-byte the PR 10/12 one:

* ``prefix_cache=True`` — prompts chain-hash per full KV block and
  adopt published pool blocks (:mod:`tony_tpu.serve.kvcache`'s prefix
  tier) instead of recomputing the shared prefix: the corresponding
  prefill launches are simply never issued. Bitwise transparent — an
  adopted block holds exactly the bytes the skipped launch would have
  written (row independence at tile multiples), and every KV scatter
  goes through the cache's copy-on-write ``write_index`` so a shared
  block is never mutated;
* ``prefill_chunk=N`` — prompts prefill in fixed ``N``-row chunks (a
  ``q_block`` multiple), one chunk per engine iteration, interleaved
  with decode: a long admission costs the running batch one extra
  launch per token step instead of a whole-prompt stall. The chunk
  geometry is the only new compiled shape, pinned by the ``route``
  analyze signature.

The decode step is registered with the collective planner at build time
(:func:`tony_tpu.profiler.record_collective`, plane ``serve_decode``)
with an EMPTY expected set: a replica's decode touches no inter-chip
collective — its mesh exists for memory, not for cross-replica math —
and ``tony analyze --config serve`` audits the traced step against that
promise (a GSPMD-inserted reshard is a finding, not a slowdown).
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu._trace import trace_record
from tony_tpu.compat import mesh_context
from tony_tpu.serve import prefix as prefix_mod
from tony_tpu.serve.disagg import HandoffError, decode_f32, encode_f32
from tony_tpu.serve.kvcache import AdmissionError, PagedKVCache

_record = functools.partial(trace_record, "serve")


@dataclasses.dataclass
class Request:
    """One generation request. ``max_new_tokens`` is a hard cap; the
    engine reserves pool blocks for ``len(tokens) + max_new_tokens`` at
    admission so decode can never exhaust the pool mid-flight."""
    rid: Any
    tokens: List[int]
    max_new_tokens: int
    # Conversation handle (opaque; the router passes its session id).
    # Non-None arms parking on an engine with the host tier: eviction
    # parks the sequence's KV under this handle instead of dropping it,
    # and the NEXT request carrying the same handle resumes from the
    # parked blocks instead of re-prefilling the shared history.
    conv: Optional[Any] = None
    # Tenant tag (tony_tpu.serve.qos): names the request's QoS class on
    # a budget-armed engine and keys the per-tenant heartbeat breakdown.
    # None (the default) bypasses budgets entirely — the untagged path
    # is byte-identical to an engine without QoS.
    tenant: Optional[str] = None


@dataclasses.dataclass
class Completion:
    """One finished request: the generated tokens, per-position f32
    logits when the engine keeps them (``keep_logits=True`` — the test
    pin surface), and the request's wall latency."""
    rid: Any
    prompt: List[int]
    tokens: List[int]
    logits: Optional[List[np.ndarray]]
    latency_s: float

    def wire(self) -> Dict[str, Any]:
        """THE serving wire form (the replica RPC verbs all speak it;
        the jax-free router duck-types the same shape in
        ``router._wire_completion`` since it cannot import this
        class)."""
        return {"rid": self.rid, "tokens": list(self.tokens),
                "latency_ms": round(1e3 * self.latency_s, 3)}


class _Seq:
    __slots__ = ("rid", "tokens", "n_prompt", "remaining", "logits",
                 "t_submit", "pf_pos", "published", "hkey", "conv",
                 "tenant", "qcharge")

    def __init__(self, req: Request, t_submit: float):
        self.rid = req.rid
        self.conv = req.conv
        self.tenant = getattr(req, "tenant", None)
        # Device blocks charged to this sequence's tenant at admission
        # (0 on untagged or un-budgeted engines); _evict releases it.
        self.qcharge = 0
        self.tokens: List[int] = list(req.tokens)
        self.n_prompt = len(req.tokens)
        self.remaining = int(req.max_new_tokens)
        self.logits: List[np.ndarray] = []
        self.t_submit = t_submit
        # Prefill cursor: the next position whose row is still
        # uncomputed (admission sets it past an adopted shared prefix;
        # chunked prefill advances it chunk by chunk).
        self.pf_pos = 0
        # Prefix-publication cursor: blocks [0, published) are indexed
        # under their chain keys; ``hkey`` is the chain state (the last
        # published block's key) so extension never rehashes history.
        self.published = 0
        self.hkey = ""


def _bucket_of(buckets: Sequence[int], n: int) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"batch {n} exceeds the largest decode bucket "
                     f"{max(buckets)}")


def build_step_fn(model: Any, *, n_layers: int, n_blocks: int,
                  block_size: int, kv_dim: int, ctx_pad: int, b: int,
                  t: int) -> Callable:
    """The (b, t)-shaped jitted serve step over a paged pool: gather
    each sequence's blocks into the fixed-extent KV buffers, run the
    serve forward, commit the fresh rows back to the pool through the
    host-computed flat scatter indices (OOB rows drop). Pools are
    donated — callers immediately rebind them, so the update is
    in-place-ish.

    Module-level so the speculative lane's draft model
    (:mod:`tony_tpu.serve.spec`) runs the IDENTICAL program over its own
    pool: one builder, one jaxpr shape family, one signature pin."""
    L, nb, bs, kvd, ctx = n_layers, n_blocks, block_size, kv_dim, ctx_pad

    def fn(params, pool_k, pool_v, tokens, positions, tables,
           flat_idx):
        # mode="clip", NOT the default NaN-fill: table padding (and
        # the scratch reference's contiguous table on a small pool)
        # may point past the pool, and those positions are masked by
        # the attention — but only 0 x FINITE is exactly 0; a
        # NaN-filled block would poison every masked row.
        kbuf = jnp.take(pool_k, tables, axis=1,
                        mode="clip").reshape(L, b, ctx, kvd)
        vbuf = jnp.take(pool_v, tables, axis=1,
                        mode="clip").reshape(L, b, ctx, kvd)
        logits, (knew, vnew) = model.apply(
            {"params": params}, tokens, positions=positions,
            kv=(kbuf, vbuf))
        pk = pool_k.reshape(L, nb * bs, kvd).at[:, flat_idx].set(
            knew.astype(pool_k.dtype), mode="drop")
        pv = pool_v.reshape(L, nb * bs, kvd).at[:, flat_idx].set(
            vnew.astype(pool_v.dtype), mode="drop")
        return (logits, pk.reshape(L, nb, bs, kvd),
                pv.reshape(L, nb, bs, kvd))

    return jax.jit(fn, donate_argnums=(1, 2))


class PagedModelRunner:
    """Shared geometry + jitted-step plumbing over ONE model and ONE
    paged KV pool: the base of both the serve engine and the
    speculative lane's draft model (:class:`tony_tpu.serve.spec.
    ModelDraft`). Owning it here keeps the two lanes on one jit cache
    shape, one mesh/donation discipline, and one forward counter idiom —
    a change to how a step runs cannot drift between them."""

    def _init_paged(self, model: Any, params: Any, *, ctx_max: int,
                    block_size: int, q_block: int,
                    decode_buckets: Sequence[int], max_running: int,
                    n_blocks: Optional[int], mesh: Optional[Any],
                    host_blocks: int = 0,
                    async_offload: bool = False,
                    aot_cache: Optional[Any] = None) -> None:
        cfg = model.cfg
        if q_block % 8:
            raise ValueError(f"q_block must be a sublane-tile multiple "
                             f"(8), got {q_block}")
        self.model = model
        self.params = params
        self.mesh = mesh
        self.q_block = int(q_block)
        self.decode_buckets = tuple(sorted(set(
            list(decode_buckets) + [max_running])))
        self.max_running = int(max_running)
        self.n_layers = cfg.n_layers
        self.kv_dim = cfg.n_kv_heads * cfg.head_dim
        self.block_size = int(block_size)
        nb_max = -(-int(ctx_max) // self.block_size)
        self.nb_max = nb_max
        self.ctx_pad = nb_max * self.block_size
        if n_blocks is None:
            n_blocks = nb_max * self.max_running
        self.cache = PagedKVCache(self.n_layers, self.kv_dim,
                                  n_blocks=n_blocks,
                                  block_size=self.block_size,
                                  dtype=cfg.dtype,
                                  host_blocks=host_blocks,
                                  async_offload=async_offload)
        self._fns: Dict[Tuple[int, int], Callable] = {}
        # AOT compile cache (tony_tpu.ckpt.aot — the replica cold-start
        # plane): executables resolved through the cache live in a
        # PARALLEL dict so the raw jitted Wrapped in _fns stays what the
        # analysis hooks (decode_traced / prefill_traced) trace — the
        # cache must never change the traced program, only who compiles
        # it. With no cache and no warm(), _aot_fns stays empty and the
        # launch path is byte-for-byte the raw jit.
        self.aot_cache = aot_cache
        self._aot_fns: Dict[Tuple[int, int], Callable] = {}
        self.aot_hits = 0
        self.aot_misses = 0
        self.fresh_compiles = 0
        self.compile_ms = 0.0
        self.deserialize_ms = 0.0
        # Forward-launch counter (prefills + decode/verify steps): the
        # machine-independent cost of a schedule — on an accelerator the
        # forward dominates wall time, so fewer launches for the same
        # tokens IS the batching/speculation win.
        self.forwards = 0
        # Weight publication (tony_tpu.publish / serve.swap): which
        # published pointer version (and its ckpt step) the live params
        # came from — 0/0 until a publication is known. The version
        # rides every stats publish so the router and the history plane
        # can prove which weights answered which request; ``swapping``
        # gates admission during a hot swap's quiesce window (and rides
        # the heartbeat so the router down-marks the replica).
        self.weight_version = 0
        self.weight_step = 0
        self.weight_swaps = 0
        self.swapping = False

    def _fn(self, b: int, t: int) -> Callable:
        """The cached view of :func:`build_step_fn` — prefill, decode,
        AND the speculative lane's k+1-row verification all share these
        entries (verification is a decode-shaped launch with more real
        rows, so it adds zero compiles)."""
        key = (b, t)
        if key not in self._fns:
            self._fns[key] = build_step_fn(
                self.model, n_layers=self.n_layers,
                n_blocks=self.cache.n_blocks, block_size=self.block_size,
                kv_dim=self.kv_dim, ctx_pad=self.ctx_pad, b=b, t=t)
        return self._fns[key]

    def _example_args(self, b: int, t: int) -> Tuple:
        """Shape-exact example arguments of the (b, t) step — the ONE
        aval source for lowering (:meth:`_compile_step`) and the
        analysis hooks (:meth:`ServeEngine.decode_traced` /
        :meth:`ServeEngine.prefill_traced`), so what the AOT path
        compiles can never drift from what the analyzer audits."""
        return (self.params, self.cache.k, self.cache.v,
                jnp.zeros((b, t), jnp.int32),
                jnp.zeros((b, t), jnp.int32),
                jnp.zeros((b, self.nb_max), jnp.int32),
                jnp.full((b, t), self.cache.oob_index, jnp.int32))

    def _aot_fingerprint(self, b: int, t: int) -> Dict[str, Any]:
        """The (b, t) step program's cache identity: mesh topology, the
        full build_step_fn geometry, the model config, and the
        params/pool aval digest — plus the jax/jaxlib/XLA runtime half
        make_fingerprint adds. Anything here drifting is a MISS."""
        from tony_tpu.ckpt import aot
        cfg = getattr(self.model, "cfg", None)
        return aot.make_fingerprint(
            "serve_step", mesh=self.mesh,
            geometry={"n_layers": self.n_layers,
                      "n_blocks": self.cache.n_blocks,
                      "block_size": self.block_size,
                      "kv_dim": self.kv_dim, "ctx_pad": self.ctx_pad,
                      "b": int(b), "t": int(t), "donate": [1, 2]},
            model=f"{type(self.model).__name__}:{cfg!r}",
            tree=(self.params, self.cache.k, self.cache.v))

    def _compile_step(self, b: int, t: int) -> Callable:
        """Lower + compile the (b, t) program ahead of time (counted in
        ``fresh_compiles``/``compile_ms``) — the same jitted function
        the default path runs, so the resulting executable is the
        IDENTICAL program, just compiled now instead of at first
        launch."""
        t0 = time.monotonic()
        jitted = self._fn(b, t)
        args = self._example_args(b, t)
        if self.mesh is not None:
            with mesh_context(self.mesh):
                compiled = jitted.lower(*args).compile()
        else:
            compiled = jitted.lower(*args).compile()
        self.compile_ms += 1e3 * (time.monotonic() - t0)
        self.fresh_compiles += 1
        return compiled

    def _resolve_aot(self, b: int, t: int) -> Callable:
        """One (b, t) executable through the AOT cache: deserialize on
        hit (milliseconds), trace+compile AND populate on miss. The
        cache degrades to a counted miss on any corruption, fingerprint
        drift, or unsupported backend — it may cost a compile, never a
        wrong program."""
        fp = self._aot_fingerprint(b, t)
        t0 = time.monotonic()
        compiled = self.aot_cache.get(fp)
        if compiled is not None:
            self.deserialize_ms += 1e3 * (time.monotonic() - t0)
            self.aot_hits += 1
            return compiled
        self.aot_misses += 1
        compiled = self._compile_step(b, t)
        self.aot_cache.put(fp, compiled)
        return compiled

    def _step_callable(self, b: int, t: int) -> Callable:
        """What :meth:`_run_fn` launches for shape (b, t): the raw
        jitted Wrapped when nothing armed the AOT plane (the default
        engine, byte for byte), else the resolved Compiled — from the
        cache on hit, freshly compiled (and persisted) on miss."""
        fn = self._aot_fns.get((b, t))
        if fn is None:
            if self.aot_cache is None:
                return self._fn(b, t)
            fn = self._resolve_aot(b, t)
            self._aot_fns[(b, t)] = fn
        return fn

    def warm(self, prefill_pads: Sequence[int] = ()) -> int:
        """Resolve the engine's enumerable step family NOW — every
        decode bucket (the speculative verify launch rides the same
        shapes), the chunked-prefill program, and any caller-named
        extra prefill pads — so a warm-standby replica holds compiled
        executables BEFORE its first request: cache hits deserialize in
        milliseconds; cold misses pay the trace+compile here, ahead of
        the traffic curve, and populate the cache for the whole fleet.
        Returns programs resolved."""
        shapes = [(int(b), self.q_block) for b in self.decode_buckets]
        chunk = getattr(self, "prefill_chunk", None)
        if chunk:
            shapes.append((1, int(chunk)))
        for p in prefill_pads:
            shapes.append((1, int(p)))
        n = 0
        for key in dict.fromkeys(shapes):
            if key not in self._aot_fns:
                self._aot_fns[key] = (
                    self._resolve_aot(*key) if self.aot_cache is not None
                    else self._compile_step(*key))
                n += 1
        return n

    def _run_fn(self, b, t, tokens, positions, tables, flat_idx):
        fn = self._step_callable(b, t)
        args = (self.params, self.cache.k, self.cache.v,
                jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(tables), jnp.asarray(flat_idx))
        if self.mesh is not None:
            with mesh_context(self.mesh):
                logits, pk, pv = fn(*args)
        else:
            logits, pk, pv = fn(*args)
        self.cache.k, self.cache.v = pk, pv
        self.forwards += 1
        return logits

    def swap_params(self, new_params: Any, *, version: int,
                    step: int) -> None:
        """Flip the live param tree to ``new_params`` — the hot-swap
        plane's commit point (tony_tpu.serve.swap). The CALLER owns the
        iteration-boundary contract: no launch may be in flight (the
        replica runs this under the front's drive lock after a
        quiesce), because ``_run_fn`` reads ``self.params`` fresh per
        launch and the flip is a single reference store — the next
        launch runs the new weights whole, no launch ever sees a mix.

        Atomic-or-rolled-back: the new tree must match the old one's
        structure, shapes, and dtypes EXACTLY — any drift raises
        :class:`~tony_tpu.serve.swap.SwapError` with the old params
        still live (a publication whose manifest changed geometry needs
        a restart, not a swap). A same-geometry flip is what keeps the
        compiled plane valid: the AOT fingerprint digests avals, not
        values, so every jitted/AOT executable survives — a swap costs
        zero recompiles."""
        from tony_tpu.serve.swap import SwapError

        old_leaves, old_def = jax.tree.flatten(self.params)
        new_leaves, new_def = jax.tree.flatten(new_params)
        if old_def != new_def:
            raise SwapError(
                f"param tree structure changed: {len(old_leaves)} vs "
                f"{len(new_leaves)} leaves — the published manifest is "
                f"not this engine's geometry; old weights kept")
        for i, (o, n) in enumerate(zip(old_leaves, new_leaves)):
            if o.shape != n.shape or o.dtype != n.dtype:
                raise SwapError(
                    f"param leaf {i} changed aval: {o.shape}/{o.dtype} "
                    f"-> {n.shape}/{n.dtype}; old weights kept")
        self.params = new_params
        self.weight_version = int(version)
        self.weight_step = int(step)
        self.weight_swaps += 1


class ServeEngine(PagedModelRunner):
    """Continuous-batching loop for one replica.

    ``model`` is a serve-capable flax module (today:
    :class:`tony_tpu.models.transformer.Transformer` — its ``kv=``
    forward); ``params`` its (restored, typically bf16) param tree.
    ``mesh`` wraps every jitted call in the replica's mesh context so
    sharded params compute in place; ``None`` runs on the default
    device placement.
    """

    def __init__(self, model: Any, params: Any, *, ctx_max: int,
                 block_size: int = 16, n_blocks: Optional[int] = None,
                 q_block: int = 16, decode_buckets: Sequence[int] = (4, 16),
                 max_running: int = 16, mesh: Optional[Any] = None,
                 keep_logits: bool = False, join_policy: str = "continuous",
                 stats_window_s: float = 60.0, tag: str = "serve",
                 prefix_cache: bool = False,
                 prefill_chunk: Optional[int] = None,
                 role: str = "colocated", host_blocks: int = 0,
                 async_offload: bool = False,
                 aot_cache: Optional[Any] = None,
                 warm_standby: bool = False,
                 demote_watermark: float = 0.0, demote_batch: int = 0,
                 qos: Optional[Any] = None):
        if join_policy not in ("continuous", "static"):
            raise ValueError(f"unknown join_policy {join_policy!r} "
                             "(continuous|static)")
        if role not in ("colocated", "prefill", "decode"):
            raise ValueError(f"unknown role {role!r} "
                             "(colocated|prefill|decode)")
        if not 0.0 <= float(demote_watermark) <= 1.0:
            raise ValueError(f"demote_watermark must be a pool fraction "
                             f"in [0, 1], got {demote_watermark}")
        self._init_paged(model, params, ctx_max=ctx_max,
                         block_size=block_size, q_block=q_block,
                         decode_buckets=decode_buckets,
                         max_running=max_running, n_blocks=n_blocks,
                         mesh=mesh, host_blocks=host_blocks,
                         async_offload=async_offload,
                         aot_cache=aot_cache)
        # Prefix caching (off by default — the unrouted PR 10/12
        # behavior): admission chain-hashes the prompt's full blocks and
        # adopts published matches instead of recomputing them. Bitwise
        # transparent by the row-independence contract; the route tests
        # pin hit and miss against this engine with the knob off.
        self.prefix_cache = bool(prefix_cache)
        # Chunked prefill (None = monolithic): long prompts prefill in
        # fixed row-block-multiple chunks interleaved with decode
        # iterations, so one long admission never stalls every running
        # sequence's next token for a whole-prompt launch.
        if prefill_chunk is not None:
            prefill_chunk = int(prefill_chunk)
            if prefill_chunk <= 0 or prefill_chunk % self.q_block:
                raise ValueError(
                    f"prefill_chunk must be a positive q_block="
                    f"{self.q_block} multiple, got {prefill_chunk}")
        self.prefill_chunk = prefill_chunk
        # Disaggregated serving role (tony_tpu.serve.disagg): telemetry
        # + router dispatch semantics. The engine itself stays fully
        # capable whatever the role — a "decode" replica still prefills
        # for itself on the colocated-fallback path, and "colocated"
        # (the default) is byte-for-byte the PR 10/12/13 engine.
        self.role = role
        # Handoff counters (the widened heartbeat schema — zeros on
        # colocated engines so the fleet schema stays uniform).
        self.blocks_shipped = 0
        self.handoff_ms = 0.0
        self.imports_failed = 0
        self.handoffs_out = 0
        self.handoffs_in = 0
        # KV memory hierarchy (PR 16): with a host tier armed
        # (host_blocks > 0), eviction PARKS a conversation-tagged
        # sequence instead of dropping its KV, and the conversation's
        # next turn resumes from the parked blocks through the atomic
        # import path — no re-prefill of the shared history. The map is
        # conversation handle -> {"tokens": full parked token history,
        # "rid": the parked cache record's id}.
        self.host_offload = host_blocks > 0
        # Warm-standby membership (the cold-start plane's pool half):
        # a standby replica is compiled-and-idle — it heartbeats
        # warm_standby=1 so the session keeps it out of the routable
        # endpoint set and the autoscaler's active count, until the AM
        # promotes it (rpc_promote -> engine.promote()) on a scale-up
        # instead of paying a cold grant.
        self.warm_standby = bool(warm_standby)
        # Demotion daemon (ROADMAP KV follow-on; OFF by default): at
        # the high watermark the engine loop demotes a batch of cold
        # cached-tier blocks to host RAM ahead of pool pressure — the
        # same "be ready before the work arrives" story as the warm
        # pool. Batch default nb_max: one context extent per sweep,
        # the ROOFLINE §12 link unit (a demotion is one batched
        # device->host fetch, so the batch sizes the PCIe transfer).
        self.demote_watermark = float(demote_watermark)
        self.demote_batch = int(demote_batch) or self.nb_max
        self.daemon_demotions = 0
        self._parked: Dict[Any, Dict[str, Any]] = {}
        self.park_hits = 0
        self.park_lookups = 0
        # Typed degrades: promotion/resume failures that fell back to
        # re-prefill (pool pressure or a corrupt host payload) — the
        # hierarchy may cost recompute, never a wedge or a wrong byte.
        self.host_degraded = 0
        # Persistent prefix store bookkeeping: chain-parent links (to
        # walk a hot tip back to its root when exporting a stem) and
        # the most-recently-adopted tips (the export candidates).
        self._chain_parent: "OrderedDict[str, str]" = OrderedDict()
        self._hot_tips: "OrderedDict[str, None]" = OrderedDict()
        self._stored_tips: set = set()
        self.store_adopted = 0
        self.keep_logits = keep_logits
        self.join_policy = join_policy
        self.tag = tag
        # Per-tenant QoS (tony_tpu.serve.qos.QosPolicy; None = off — the
        # byte-identical untagged path). The policy gates the ADMISSION
        # scan only: the paged pool's refcount/free/LRU partition never
        # sees tenants; an over-budget tenant's requests simply wait in
        # the queue while later tenants' requests admit past them.
        self.qos = qos
        # Device blocks currently reserved per tenant (admission extent,
        # released at eviction) + lifetime per-tenant completions — the
        # heartbeat breakdown and the budget denominator's active set.
        self._tenant_blocks: Dict[str, int] = {}
        self._tenant_completed: Dict[str, int] = {}
        self.admission_rejections = 0
        self.qos_deferrals = 0
        self._queue: deque = deque()
        self._lock = threading.Lock()
        self._running: List[_Seq] = []
        self._prefilling: List[_Seq] = []
        # Prefix/prefill telemetry (lifetime counters; the heartbeat
        # schema publishes the derived rate — zeros when the features
        # are off, so the fleet schema stays uniform).
        self.prefix_lookup_blocks = 0
        self.prefix_hit_blocks = 0
        self.prefill_launches = 0
        self.prefill_rows = 0
        self.prefill_chunks = 0
        # Prompt-length histogram, bucketed by the PADDED prefill length
        # (the compile-relevant quantity): submit-time counts keyed by
        # the q_block-multiple pad a monolithic prefill of that prompt
        # launches at. Rides stats() as the one dict-of-scalars next to
        # tenants, and the SERVE_WINDOW event log accumulates it — the
        # warm() pad self-tuner (serve.swap.derive_prefill_pads) reads
        # the logged histogram back instead of a caller guessing
        # prefill_pads by hand.
        self._prompt_hist: Dict[int, int] = {}
        # Telemetry: completion ring for p50/p99, monotonic counters for
        # rates — O(1) per step, million-request safe.
        # (t_done, latency_s, n_tokens) per completion: rates and
        # percentiles are computed over a TIME window, not lifetime —
        # the autoscaler reads p99/qps as "now", and a latency spike
        # from an hour-old burst must age out or scale-down never fires.
        self._events: deque = deque(maxlen=512)
        self.stats_window_s = float(stats_window_s)
        self._completed = 0
        self._tokens_out = 0           # tokens of COMPLETED requests
        self._emitted = 0              # every generated token, at emit
        self._t0 = time.monotonic()
        self._steps = 0
        self.register_plan()

    # -- planner/profiler registration ------------------------------------
    def register_plan(self) -> None:
        """Register the decode step's (empty) collective schedule with
        the unified planner record plus the engine geometry — the
        day-one registration ROADMAP asks of every new step-path plane;
        ``tony analyze --config serve`` audits the traced decode against
        exactly this promise."""
        trace_record("collective", "serve_decode", kind="none",
                     plane="serve_decode", axes=[], nbytes=[],
                     note="replica-local decode: zero inter-chip "
                          "collectives")
        _record(self.tag, ctx_pad=self.ctx_pad,
                block_size=self.block_size, nb_max=self.nb_max,
                n_blocks=self.cache.n_blocks, q_block=self.q_block,
                decode_buckets=list(self.decode_buckets),
                max_running=self.max_running,
                join_policy=self.join_policy,
                prefix_cache=self.prefix_cache,
                prefill_chunk=self.prefill_chunk,
                role=self.role)

    def expected_collectives(self) -> list:
        """The planner-registered expected collective set of the decode
        step: empty — a replica mesh shards memory, never the decode
        math. The analyzer reconciles the traced program against this."""
        return []

    # -- admission ---------------------------------------------------------
    def submit(self, req: Request) -> None:
        """Queue a request (thread-safe). Requests that can NEVER fit
        the context buffer are rejected now with a non-retryable
        :class:`AdmissionError`; pool pressure is handled later, at
        join time, by leaving the request queued."""
        total = len(req.tokens) + req.max_new_tokens
        if not req.tokens:
            raise ValueError(f"request {req.rid!r}: empty prompt")
        needed = self.cache.blocks_for(total)
        if total > self.ctx_pad or needed > self.cache.n_blocks:
            # Over the context extent OR over the ENTIRE pool (an
            # explicit small n_blocks): queueing it as retryable would
            # livelock the loop — join would re-raise forever with
            # nothing ever freeing enough.
            raise AdmissionError(
                f"request {req.rid!r} needs {total} positions "
                f"({needed} blocks) > engine capacity (context "
                f"{self.ctx_pad}, pool {self.cache.n_blocks} blocks); "
                f"it can never be admitted",
                needed_blocks=needed,
                free_blocks=self.cache.free_blocks, retryable=False)
        tenant = getattr(req, "tenant", None)
        with self._lock:
            if self.qos is not None and tenant is not None \
                    and self.qos.max_queue:
                depth = sum(1 for r, _ in self._queue
                            if getattr(r, "tenant", None) == tenant)
                if depth >= self.qos.max_queue:
                    # Typed, retryable back-pressure to the BURSTING
                    # tenant only: its pending queue is full, so the
                    # caller backs off — the victim tenant's submits
                    # never see this path.
                    self.admission_rejections += 1
                    raise AdmissionError(
                        f"request {req.rid!r}: tenant {tenant!r} queue "
                        f"full ({depth}/{self.qos.max_queue} pending)",
                        needed_blocks=needed,
                        free_blocks=self.cache.free_blocks)
            self._queue.append((req, time.monotonic()))
            # Histogram at the padded prefill length (the shape a
            # monolithic prefill of this prompt compiles), counted only
            # for ACCEPTED submissions — the pad self-tuner must learn
            # the shapes the engine actually launches.
            pad = -(-len(req.tokens) // self.q_block) * self.q_block
            self._prompt_hist[pad] = self._prompt_hist.get(pad, 0) + 1

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def running(self) -> int:
        # Chunk-prefilling sequences hold pool blocks and engine work —
        # they are in-flight for every queue/occupancy consumer.
        return len(self._running) + len(self._prefilling)

    # -- prefill -----------------------------------------------------------
    def _prefill_span(self, seq: _Seq, c1: int, t_pad: int) -> None:
        """One prefill launch over positions ``[seq.pf_pos, c1)`` padded
        to ``t_pad`` rows — the whole remaining prompt (monolithic), one
        chunk (chunked), or the tail re-computation after a full prefix
        hit. Rows attend to earlier positions through the pool gather
        and to each other through the forward's in-buffer scatter, so
        the split point cannot change a bit (the route tests pin chunked
        vs monolithic). Emits the first token when ``c1`` completes the
        prompt."""
        c0 = seq.pf_pos
        t_real = c1 - c0
        n = len(seq.tokens)
        tokens = np.zeros((1, t_pad), np.int32)
        tokens[0, :t_real] = seq.tokens[c0:c1]
        positions = (c0 + np.arange(t_pad, dtype=np.int32))[None].copy()
        flat = np.full((1, t_pad), self.cache.oob_index, np.int32)
        for j in range(t_real):
            # write_index, not flat_index: a fully-matched admission's
            # tail row lands in an adopted block — the writer must own a
            # private copy first (COW; pre-copied at admission).
            flat[0, j] = self.cache.write_index(seq.rid, c0 + j)
        tables = self.cache.table_array([seq.rid], self.nb_max)
        logits = self._run_fn(1, t_pad, tokens, positions, tables, flat)
        self.prefill_launches += 1
        self.prefill_rows += t_pad
        seq.pf_pos = c1
        if c1 >= n:
            last = np.asarray(logits[0, n - 1 - c0], np.float32)
            self._emit_token(seq, last)
        else:
            self._publish(seq)

    def _prefill(self, seq: _Seq) -> None:
        """Monolithic prefill of everything past the prefill cursor."""
        t_real = len(seq.tokens) - seq.pf_pos
        t_pad = -(-t_real // self.q_block) * self.q_block
        self._prefill_span(seq, len(seq.tokens), t_pad)

    def _prefill_chunk_step(self, seq: _Seq) -> bool:
        """Advance one chunk; True when the prompt completed (and the
        first token was emitted). Non-final chunks launch at the fixed
        ``(1, prefill_chunk)`` shape; the final chunk pads its remainder
        to a row-block multiple — the whole declared chunk geometry the
        ``route`` analyze signature pins."""
        n = len(seq.tokens)
        c1 = min(n, seq.pf_pos + self.prefill_chunk)
        t_real = c1 - seq.pf_pos
        t_pad = (self.prefill_chunk if c1 < n
                 else -(-t_real // self.q_block) * self.q_block)
        self._prefill_span(seq, c1, t_pad)
        self.prefill_chunks += 1
        return seq.pf_pos >= n

    # -- prefix publication ------------------------------------------------
    def _publish(self, seq: _Seq) -> None:
        """Index every newly-completed block under its chain key. The
        publishable extent is ``len(tokens) - 1``: rows strictly below
        it are verified-written on every path (after prefill+emit, after
        a decode emit, and after a verify round's commit — the spec
        engine's accepted rows were computed from true tokens), so a
        published block can never leak a draft byte."""
        if not self.prefix_cache:
            return
        bs = self.block_size
        # Written extent: the prefill cursor until the prompt is done,
        # then every row below the newest token (each decode/verify
        # feeds and writes the row below the token it emits).
        limit = (len(seq.tokens) - 1 if seq.pf_pos >= seq.n_prompt
                 else seq.pf_pos)
        while (seq.published + 1) * bs <= limit:
            i = seq.published
            key = prefix_mod.chain_keys(
                seq.tokens[i * bs:(i + 1) * bs], bs, prior=seq.hkey)[0]
            self.cache.publish_block(seq.rid, i, key)
            self._note_parent(key, seq.hkey)
            seq.hkey = key
            seq.published += 1

    def _note_parent(self, key: str, prior: str) -> None:
        """Record one chain link (bounded) so a hot tip can be walked
        back to its root when the persistent store exports the stem."""
        self._chain_parent[key] = prior
        self._chain_parent.move_to_end(key)
        while len(self._chain_parent) > 4096:
            self._chain_parent.popitem(last=False)

    def _note_parents(self, keys: Sequence[str]) -> None:
        for i, key in enumerate(keys):
            self._note_parent(key, keys[i - 1] if i else "")

    def _note_chain(self, keys: Sequence[str], matched: int) -> None:
        """An adoption PROVED blocks shared — remember the links and
        mark the adopted tip hot (the persistent prefix store exports
        the hottest few tips, i.e. exactly the stems a second
        conversation reused)."""
        self._note_parents(keys[:matched])
        tip = keys[matched - 1]
        self._hot_tips[tip] = None
        self._hot_tips.move_to_end(tip)
        while len(self._hot_tips) > 64:
            self._hot_tips.popitem(last=False)

    # -- decode ------------------------------------------------------------
    def _decode(self) -> None:
        seqs = list(self._running)
        b = _bucket_of(self.decode_buckets, len(seqs))
        t = self.q_block
        tokens = np.zeros((b, t), np.int32)
        positions = np.zeros((b, t), np.int32)
        tables = np.zeros((b, self.nb_max), np.int32)
        flat = np.full((b, t), self.cache.oob_index, np.int32)
        for i, s in enumerate(seqs):
            p0 = len(s.tokens) - 1          # the newest, not-yet-fed token
            tokens[i, 0] = s.tokens[-1]
            positions[i] = p0 + np.arange(t, dtype=np.int32)
            flat[i, 0] = self.cache.write_index(s.rid, p0)
        # Tables AFTER the write-index pass: write_index may COW-repoint
        # a table slot, and the gather must see the repointed table.
        tables[:len(seqs)] = self.cache.table_array(
            [s.rid for s in seqs], self.nb_max)
        logits = self._run_fn(b, t, tokens, positions, tables, flat)
        rows = np.asarray(logits[:len(seqs), 0], np.float32)
        for i, s in enumerate(seqs):
            self._emit_token(s, rows[i])

    def _emit_token(self, seq: _Seq, row: np.ndarray) -> None:
        if self.keep_logits:
            seq.logits.append(row.copy())
        seq.tokens.append(int(np.argmax(row)))   # greedy: deterministic
        seq.remaining -= 1
        self._emitted += 1
        self._publish(seq)

    # -- scheduling --------------------------------------------------------
    def _admit(self, req: Request,
               total: Optional[int] = None) -> Tuple[int, int, Sequence[str]]:
        """Reserve the request's full extent, adopting any published
        prefix blocks first; returns ``(start, matched, keys)`` — the
        prefill start position (past the adopted extent: those launches
        are simply never issued), the adopted block count, and the
        prompt's chain keys (so publication seeding never rehashes
        them). Raises :class:`AdmissionError` with the cache unchanged
        on pool pressure, so a queued request retries whole. ``total``
        overrides the reservation extent (the prefill-only mode
        reserves the PROMPT alone — the decode extent belongs to the
        replica that decodes)."""
        if total is None:
            total = len(req.tokens) + req.max_new_tokens
        if self.host_offload and req.conv is not None:
            res = self._try_resume(req, total)
            if res is not None:
                return res
        if not self.prefix_cache:
            self.cache.reserve(req.rid, total)
            return 0, 0, ()
        keys = prefix_mod.chain_keys(req.tokens, self.block_size)
        if self.host_offload:
            # Re-stage any demoted stretch of this prompt's chain from
            # the host tier before matching — the admission then adopts
            # it like any published stem. A corrupt host payload
            # degrades to recompute (the poison entry dropped so it
            # cannot fail every later admission), never an error.
            try:
                self.cache.promote(keys)
            except HandoffError:
                self.cache.discard_host(keys)
                self.host_degraded += 1
        matched = self.cache.admit_shared(req.rid, total, keys)
        m = matched * self.block_size
        if m >= len(req.tokens):
            # Full cover: the last prompt row still re-computes (its
            # logits seed generation), and its KV write lands in an
            # adopted block — take the private copy NOW, inside the
            # admission transaction, so the one COW this sequence can
            # ever need cannot fail mid-flight. If even that one spare
            # block can't be supplied, DEGRADE the match by the tail
            # block (its rows compute fresh into the reservation's own
            # blocks — no COW needed) rather than queue-spinning a
            # request the capacity check already accepted.
            try:
                self.cache.cow_block(req.rid,
                                     (len(req.tokens) - 1)
                                     // self.block_size)
            except AdmissionError:
                self.cache.free_seq(req.rid)
                matched = self.cache.admit_shared(req.rid, total,
                                                  keys[:-1])
                m = matched * self.block_size
        # Counters only after the admission definitively succeeded —
        # a pressure-retried request must not skew the published
        # prefix_cache_hit_rate with every retry.
        self.prefix_lookup_blocks += len(keys)
        self.prefix_hit_blocks += matched
        if matched:
            self._note_chain(keys, matched)
        return min(m, len(req.tokens) - 1), matched, keys

    def _park_keys(self, tokens: Sequence[int], length: int
                   ) -> List[str]:
        """Chain keys of the FULL blocks inside ``tokens[:length]`` —
        the parked record's resume-time adoption probe (one key per
        full block; a partial tail block ships keyless, exactly the
        wire contract)."""
        bs = self.block_size
        return prefix_mod.chain_keys(
            list(tokens)[:(int(length) // bs) * bs], bs)

    def _park(self, seq: _Seq) -> bool:
        """Park ``seq``'s KV under its conversation handle instead of
        freeing it. The parked extent is ``len(tokens) - 1`` — every
        row strictly below the newest token is verified-written (the
        final emitted token's row is never computed), the same bound
        :meth:`_publish` trusts. A re-park of the same conversation
        drops the stale turn first; a full host tier returns False
        (state unchanged) and eviction degrades to the plain free."""
        length = len(seq.tokens) - 1
        if length <= 0:
            return False
        old = self._parked.pop(seq.conv, None)
        if old is not None:
            self.cache.unpark(old["rid"])
        try:
            self.cache.park(seq.rid, length,
                            keys=self._park_keys(seq.tokens, length))
        except AdmissionError:
            return False
        self._parked[seq.conv] = {"tokens": list(seq.tokens),
                                  "rid": seq.rid}
        return True

    def _try_resume(self, req: Request, total: int
                    ) -> Optional[Tuple[int, int, Sequence[str]]]:
        """Resume ``req`` from its conversation's parked KV: adopt what
        is still on device, re-stage the rest from the host payloads,
        and start the prefill cursor at the parked extent — the shared
        history's launches are simply never issued. Bitwise transparent
        by the chunked-prefill split-point contract: rows from the
        cursor on compute exactly what a full prefill would compute
        there. ``None`` (nothing changed beyond dropping a dead record)
        falls through to fresh admission: no parked record, a diverged
        prompt, or a typed resume failure (pool pressure / host
        corruption — counted in ``host_degraded``; the conversation
        pays a re-prefill, never a wedge)."""
        self.park_lookups += 1
        rec = self._parked.get(req.conv)
        if rec is None:
            return None
        ptoks = rec["tokens"]
        length = len(ptoks) - 1
        if len(req.tokens) < len(ptoks) \
                or list(req.tokens)[:len(ptoks)] != ptoks:
            # The turn does not extend the parked history (edited or
            # truncated conversation): the record can never be resumed
            # by a later turn either — drop it.
            self._parked.pop(req.conv, None)
            self.cache.unpark(rec["rid"])
            return None
        try:
            self.cache.resume(req.rid, total, rec["rid"])
        except (AdmissionError, HandoffError):
            self._parked.pop(req.conv, None)
            self.cache.unpark(rec["rid"])
            self.host_degraded += 1
            return None
        self._parked.pop(req.conv, None)
        self.park_hits += 1
        keys = self._park_keys(ptoks, length)
        if self.prefix_cache and keys:
            # The resumed blocks hold verified rows — index them so
            # other prompts adopt the shared history, and seed the
            # publication cursor past them (the admit_handoff idiom).
            for i, key in enumerate(keys):
                self.cache.publish_block(req.rid, i, key)
            self._note_parents(keys)
            self.prefix_lookup_blocks += len(keys)
            self.prefix_hit_blocks += len(keys)
            return length, len(keys), keys
        return length, 0, ()

    def _seed_publication(self, seq: _Seq, matched: int,
                          keys: Sequence[str]) -> None:
        """An adopted prefix is already indexed — advance the
        publication cursor past it so the sequence publishes only what
        it computes (``keys`` are the admission's chain keys; no
        rehash)."""
        if matched:
            seq.published = matched
            seq.hkey = keys[matched - 1]

    def _join(self, results: List[Completion]) -> None:
        # Hot-swap quiesce (tony_tpu.serve.swap): admission pauses while
        # the swap drains the batch — in-flight sequences complete under
        # the OLD weights, queued requests stay queued and admit AFTER
        # the flip under the new ones, so no request ever spans weight
        # versions and none is dropped.
        if self.swapping:
            return
        if self.join_policy == "static" and (self._running
                                             or self._prefilling):
            return
        if self.qos is not None:
            self._join_qos(results)
            return
        while len(self._running) + len(self._prefilling) \
                < self.max_running:
            with self._lock:
                if not self._queue:
                    return
                req, t_submit = self._queue[0]
            try:
                start, matched, keys = self._admit(req)
            except AdmissionError:
                return                      # pool pressure: stay queued
            with self._lock:
                self._queue.popleft()
            seq = _Seq(req, t_submit)
            seq.pf_pos = start
            self._seed_publication(seq, matched, keys)
            if self.prefill_chunk is not None:
                # Chunked: the prompt advances one chunk per engine
                # iteration, interleaved with decode — admission never
                # stalls the running batch for a whole-prompt launch.
                self._prefilling.append(seq)
                continue
            self._prefill(seq)
            if seq.remaining <= 0:          # max_new_tokens == 1
                self._evict(seq, results)
            else:
                self._running.append(seq)

    def _qos_active(self) -> set:
        """The budget denominator's active-tenant set: tenants holding
        device blocks or waiting in the queue (caller holds the lock).
        Work conservation falls out — an idle tenant leaves the set and
        its share redistributes."""
        active = {t for t, n in self._tenant_blocks.items() if n > 0}
        for r, _ in self._queue:
            t = getattr(r, "tenant", None)
            if t is not None:
                active.add(t)
        return active

    def _join_qos(self, results: List[Completion]) -> None:
        """The budget-armed admission scan: walk the queue in FIFO
        order, DEFER requests whose tenant is over its weighted-fair
        block budget (and every later request of that tenant — per-
        tenant order is preserved), admit the first request that fits.
        Untagged requests bypass budgets. Pool pressure from ``_admit``
        ends the scan whole, exactly like the unarmed path — the
        deferral mechanism is skip-over, never reorder-within-tenant
        and never eviction."""
        blocked: set = set()
        while len(self._running) + len(self._prefilling) \
                < self.max_running:
            picked = None
            with self._lock:
                if not self._queue:
                    return
                active = self._qos_active()
                for i, (req, t_submit) in enumerate(self._queue):
                    tenant = getattr(req, "tenant", None)
                    if tenant is None:
                        picked = (i, req, t_submit)
                        break
                    if tenant in blocked:
                        continue
                    needed = self.cache.blocks_for(
                        len(req.tokens) + req.max_new_tokens)
                    budget = self.qos.budget(
                        tenant, self.cache.n_blocks, active)
                    if self._tenant_blocks.get(tenant, 0) + needed \
                            > budget:
                        blocked.add(tenant)
                        self.qos_deferrals += 1
                        continue
                    picked = (i, req, t_submit)
                    break
            if picked is None:
                return                     # every waiter is over budget
            i, req, t_submit = picked
            try:
                start, matched, keys = self._admit(req)
            except AdmissionError:
                return                      # pool pressure: stay queued
            with self._lock:
                # Index i is still req's slot: submit only APPENDS and
                # this drive thread is the only popper (the front's
                # single-driver contract).
                del self._queue[i]
                tenant = getattr(req, "tenant", None)
                if tenant is not None:
                    charge = self.cache.blocks_for(
                        len(req.tokens) + req.max_new_tokens)
                    self._tenant_blocks[tenant] = \
                        self._tenant_blocks.get(tenant, 0) + charge
            seq = _Seq(req, t_submit)
            if seq.tenant is not None:
                seq.qcharge = self.cache.blocks_for(
                    len(req.tokens) + req.max_new_tokens)
            seq.pf_pos = start
            self._seed_publication(seq, matched, keys)
            if self.prefill_chunk is not None:
                self._prefilling.append(seq)
                continue
            self._prefill(seq)
            if seq.remaining <= 0:          # max_new_tokens == 1
                self._evict(seq, results)
            else:
                self._running.append(seq)

    def _evict(self, seq: _Seq, results: List[Completion]) -> None:
        # Conversation parking: a host-tier engine keeps a finished
        # conversation-tagged turn's KV (demoted to host RAM) instead
        # of dropping it — the next turn resumes where this one ended.
        # cache.park frees the device reservation itself; a full host
        # tier degrades to the plain free below.
        if not (self.host_offload and seq.conv is not None
                and self._park(seq)):
            self.cache.free_seq(seq.rid)
        now = time.monotonic()
        # Under the lock: the stats publisher thread (replica heartbeat)
        # iterates this ring concurrently with the drive thread, and a
        # deque mutated mid-iteration raises — found by the concurrency
        # lint's guarded-elsewhere rule, pinned by test_concurrency.
        with self._lock:
            self._events.append((now, now - seq.t_submit,
                                 len(seq.tokens) - seq.n_prompt,
                                 seq.tenant))
            if seq.tenant is not None:
                if seq.qcharge:
                    left = self._tenant_blocks.get(seq.tenant, 0) \
                        - seq.qcharge
                    if left > 0:
                        self._tenant_blocks[seq.tenant] = left
                    else:
                        self._tenant_blocks.pop(seq.tenant, None)
                self._tenant_completed[seq.tenant] = \
                    self._tenant_completed.get(seq.tenant, 0) + 1
        self._completed += 1
        self._tokens_out += len(seq.tokens) - seq.n_prompt
        results.append(Completion(
            rid=seq.rid, prompt=seq.tokens[:seq.n_prompt],
            tokens=seq.tokens[seq.n_prompt:],
            logits=seq.logits if self.keep_logits else None,
            latency_s=now - seq.t_submit))

    def _advance_prefill(self, results: List[Completion]) -> None:
        """One chunk for the oldest prefilling sequence (FIFO — one
        chunk per iteration keeps the decode cadence: a long prompt
        costs ONE extra launch per running-batch token step, not a
        whole-prompt stall)."""
        if not self._prefilling:
            return
        seq = self._prefilling[0]
        if self._prefill_chunk_step(seq):
            self._prefilling.pop(0)
            if seq.remaining <= 0:          # max_new_tokens == 1
                self._evict(seq, results)
            else:
                self._running.append(seq)

    # -- disaggregated prefill/decode (tony_tpu.serve.disagg) --------------
    def prefill_only(self, req: Request) -> Dict[str, Any]:
        """The prefill-role engine mode: run ``req``'s prompt through
        the normal admission + prefill path (prefix adoption, the
        chunked ``(1, chunk)`` launch family — the IDENTICAL program a
        colocated engine runs, so the handoff cannot change a bit),
        emit the FIRST token, then export the sequence's KV blocks as
        the handoff wire payload and free the sequence — the output is
        KV + one token, never a generation loop, and the engine is free
        for the next prompt the moment this returns.

        Single-driver contract: the caller (``serve.disagg.
        PrefillFront``) holds the front's drive lock — the same lock
        that serializes colocated ``generate`` callers — because every
        line here mutates the paged pool."""
        n = len(req.tokens)
        if not req.tokens:
            raise ValueError(f"request {req.rid!r}: empty prompt")
        needed = self.cache.blocks_for(n)
        if n > self.ctx_pad or needed > self.cache.n_blocks:
            raise AdmissionError(
                f"request {req.rid!r}: {n}-token prompt ({needed} "
                f"blocks) > engine capacity (context {self.ctx_pad}, "
                f"pool {self.cache.n_blocks} blocks)",
                needed_blocks=needed,
                free_blocks=self.cache.free_blocks, retryable=False)
        start, matched, keys = self._admit(req, total=n)
        seq = _Seq(req, time.monotonic())
        seq.pf_pos = start
        self._seed_publication(seq, matched, keys)
        if self.prefill_chunk is not None:
            while not self._prefill_chunk_step(seq):
                pass
        else:
            self._prefill(seq)
        first = int(seq.tokens[n])
        # Chain keys of the full prompt blocks — the decode side's
        # adoption probe AND its publication seed (always shipped:
        # adoption on the importer works even when THIS engine runs
        # with the prefix cache off).
        wire_keys = (list(keys) if self.prefix_cache
                     else prefix_mod.chain_keys(req.tokens,
                                                self.block_size))
        t_export = time.monotonic()
        payload: Dict[str, Any] = {
            "rid": req.rid,
            "tokens": [int(t) for t in req.tokens],
            "first_token": first,
            "max_new_tokens": int(req.max_new_tokens),
            "length": n,
            "conv": req.conv,
            "tenant": getattr(req, "tenant", None),
            "keys": wire_keys,
            "blocks": self.cache.export_blocks(req.rid, n),
            **self.cache.wire_header(),
        }
        if self.keep_logits:
            payload["logits_b64"] = encode_f32(seq.logits[0])
        # handoff_ms counts the time THIS engine spent moving KV bytes
        # (export here, import on the decode side) — not the shipped
        # sequence's downstream generation.
        self.handoff_ms += 1e3 * (time.monotonic() - t_export)
        self.cache.free_seq(req.rid)
        # The prefill replica's ONLY load telemetry: a handoff never
        # queues or joins the running batch, so without this event the
        # gang would heartbeat qps=0/p99=0 forever — the per-gang
        # autoscaler and the router's load scoring could never see a
        # prefill burst. The event shape mirrors _evict's (latency from
        # admission, one emitted token).
        now = time.monotonic()
        with self._lock:
            self._events.append((now, now - seq.t_submit, 1,
                                 seq.tenant))
        self._completed += 1
        self._tokens_out += 1
        return payload

    def admit_handoff(self, payload: Dict[str, Any]
                      ) -> Tuple[Any, Optional[Completion]]:
        """The decode-role admission path: import a shipped prefill's
        KV blocks into this engine's pool (:meth:`PagedKVCache.
        import_blocks` — adopting any offered shared-prefix stem) and
        join the sequence to the running batch with its prompt already
        computed, so the next iteration decodes its second token exactly
        where a colocated engine would. Returns ``(rid, completion)`` —
        ``completion`` non-None only for the degenerate
        ``max_new_tokens == 1`` handoff, whose one token the prefill
        side already produced.

        Back-pressure is a typed, state-unchanged rejection (the
        shipper's retry surface): a full decode batch or an exhausted
        pool raises :class:`AdmissionError` with NOTHING changed, and a
        corrupt payload raises :class:`~tony_tpu.serve.disagg.
        HandoffError` the same way. Single-driver contract: the caller
        (``serve.disagg.DecodeFront``) holds the front's drive lock —
        this runs on an RPC receiver thread while another thread drives
        decode, which is exactly the mutation the PR 14 concurrency
        plane gates."""
        try:
            try:
                rid = payload["rid"]
                tokens = [int(t) for t in payload["tokens"]]
                max_new = int(payload["max_new_tokens"])
                first = int(payload["first_token"])
                offset = int(payload.get("offset", 0))
            except (KeyError, TypeError, ValueError) as e:
                # A version-skewed or truncated payload must reject the
                # same way every other malformed field does — typed and
                # counted — not escape as a bare KeyError past the
                # shipper's _classify and the router's fallback split.
                raise HandoffError(
                    f"malformed handoff payload: missing or mistyped "
                    f"field ({type(e).__name__}: {e})",
                    retryable=False) from e
            n = len(tokens)
            if n != int(payload.get("length", n)) or not tokens \
                    or max_new < 1:
                raise HandoffError(
                    f"malformed handoff for {rid!r}: length "
                    f"{payload.get('length')} vs {n} prompt token(s), "
                    f"max_new_tokens {max_new}", retryable=False)
            header = self.cache.wire_header()
            got = {k: payload.get(k) for k in header}
            if got != header:
                raise HandoffError(
                    f"handoff geometry mismatch for {rid!r}: {got} vs "
                    f"this pool's {header}", retryable=False)
            total = n + max_new
            needed = self.cache.blocks_for(total)
            if total > self.ctx_pad or needed > self.cache.n_blocks:
                raise AdmissionError(
                    f"handoff {rid!r} needs {total} positions "
                    f"({needed} blocks) > engine capacity (context "
                    f"{self.ctx_pad}, pool {self.cache.n_blocks} "
                    f"blocks); it can never be admitted",
                    needed_blocks=needed,
                    free_blocks=self.cache.free_blocks, retryable=False)
            if self.running >= self.max_running:
                raise AdmissionError(
                    f"handoff {rid!r} rejected: decode batch full "
                    f"({self.running}/{self.max_running} running)",
                    needed_blocks=needed,
                    free_blocks=self.cache.free_blocks)
            # A shipped rid that is already live HERE (a caller-supplied
            # duplicate — minted rids carry a per-front namespace) must
            # reject typed before any import: admitting it would tear
            # the front's rid-keyed completion routing, and the cache's
            # own fresh-admission ValueError is not part of the
            # (AdmissionError, HandoffError) failover split.
            live = {s.rid for s in self._running} \
                | {s.rid for s in self._prefilling} \
                | set(self.cache.owned_blocks())
            with self._lock:
                live |= {r.rid for r, _ in self._queue}
            if rid in live:
                raise HandoffError(
                    f"handoff rid {rid!r} collides with a live sequence "
                    f"on this engine — rids must be unique fleet-wide",
                    retryable=False)
            # The shipped blocks (plus the adopted stem) must cover the
            # prompt EXACTLY: a truncated or absent blocks field would
            # otherwise pass every typed check — the per-block CRC only
            # guards blocks that are present — and the uncovered prompt
            # extent would decode from uninitialized pool blocks,
            # silently wrong.
            shipped = list(payload.get("blocks") or ())
            if offset + len(shipped) != self.cache.blocks_for(n):
                raise HandoffError(
                    f"handoff {rid!r} blocks do not cover the prompt: "
                    f"{offset} adopted + {len(shipped)} shipped != "
                    f"{self.cache.blocks_for(n)} prompt block(s) for "
                    f"{n} token(s)", retryable=False)
            keys = [str(k) for k in payload.get("keys") or ()]
            # The chain keys outlive this request — they index imported
            # blocks into the SHARED prefix tier below — so unlike the
            # CRC (which guards the wire, not content identity) they
            # must be verified against the tokens they claim to cover:
            # a version-skewed shipper's wrong keys would otherwise
            # poison adoptions for unrelated future prompts, silently.
            true_keys = prefix_mod.chain_keys(tokens, self.block_size)
            if keys and keys != true_keys:
                raise HandoffError(
                    f"handoff chain keys for {rid!r} do not match the "
                    f"shipped tokens ({len(keys)} key(s) vs "
                    f"{len(true_keys)} derived) — key-scheme skew "
                    f"between the gangs", retryable=False)
            first_row: Optional[np.ndarray] = None
            if self.keep_logits and payload.get("logits_b64"):
                # Decode BEFORE the import mutates the pool: logits
                # ride outside the per-block CRC, and a corrupt row
                # must reject typed and state-unchanged like every
                # other malformed field — not leak an admitted table.
                try:
                    first_row = decode_f32(payload["logits_b64"])
                except (ValueError, TypeError) as e:
                    raise HandoffError(
                        f"malformed handoff logits for {rid!r}: {e}",
                        retryable=False) from e
            t_import = time.monotonic()
            self.cache.import_blocks(rid, total, shipped, keys=keys,
                                     offset=offset)
            self.handoff_ms += 1e3 * (time.monotonic() - t_import)
        except (AdmissionError, HandoffError):
            self.imports_failed += 1
            raise
        seq = _Seq(Request(rid=rid, tokens=tokens,
                           max_new_tokens=max_new,
                           conv=payload.get("conv"),
                           tenant=payload.get("tenant")),
                   time.monotonic())
        seq.pf_pos = n                     # the prompt arrived computed
        seq.tokens.append(first)
        seq.remaining -= 1                 # the prefill side emitted it
        if first_row is not None:
            seq.logits.append(first_row)
        if self.prefix_cache and keys:
            # The imported prompt blocks hold verified rows — index
            # them under the shipped chain keys (adopted ones are
            # already indexed; publish_block no-ops) and seed the
            # publication cursor past them so decode publishes only
            # what it computes.
            for i, key in enumerate(keys):
                self.cache.publish_block(rid, i, key)
            self._note_parents(keys)
            seq.published = len(keys)
            seq.hkey = keys[-1]
        self.handoffs_in += 1
        if seq.remaining <= 0:             # max_new_tokens == 1
            done: List[Completion] = []
            self._evict(seq, done)
            return rid, done[0]
        self._running.append(seq)
        return rid, None

    def note_handoff_shipped(self, blocks: int) -> None:
        """Bank one completed outbound handoff's shipped-block count.
        Called by the shipping front (``serve.disagg.PrefillFront``) —
        possibly from CONCURRENT RPC receiver threads, the one handoff
        counter path not serialized by the front's drive lock, hence
        the engine lock here (a bare ``+=`` is a torn RMW)."""
        with self._lock:
            self.blocks_shipped += int(blocks)
            self.handoffs_out += 1

    # -- persistent prefix store (tony_tpu.serve.kvstore) ------------------
    def adopt_stem(self, keys: Sequence[str],
                   blocks: Sequence[Dict[str, Any]]) -> int:
        """Seed the prefix tier from a persisted stem (replica startup,
        or a scale-up grant naming the store): import the chain's
        payloads through the SAME verify-then-commit path a handoff
        rides, publish them, and release the scratch reservation so the
        blocks land in the refcount-0 cached tier — exactly where a
        local conversation's published stem would sit. Best-effort by
        design: a corrupt chunk or pool pressure returns 0 adopted
        blocks (the replica warms from recompute instead), never an
        error. Returns blocks newly indexed."""
        keys = [str(k) for k in keys]
        if not self.prefix_cache or not keys \
                or len(keys) != len(blocks):
            return 0
        matched = len(self.cache.match_prefix(keys))
        if matched >= len(keys):
            return 0
        sid = ("stem", keys[-1])
        try:
            self.cache.import_blocks(
                sid, len(keys) * self.block_size,
                list(blocks)[matched:], keys=keys, offset=matched)
        except (AdmissionError, HandoffError):
            return 0
        for i, key in enumerate(keys):
            self.cache.publish_block(sid, i, key)
        self.cache.free_seq(sid)
        self._note_parents(keys)
        self.store_adopted += len(keys) - matched
        return len(keys) - matched

    def export_stems(self, store: Any, limit: int = 8) -> int:
        """Persist the hottest adopted stems (chains a SECOND prompt
        proved shared) into ``store`` (:class:`tony_tpu.serve.kvstore.
        PrefixStore`) — idempotent per tip, skipping chains whose
        blocks aged out of the device index. The caller owns the drive
        lock (the export reads the pool). Returns stems written."""
        wrote = 0
        for tip in list(self._hot_tips)[-limit:]:
            if tip in self._stored_tips:
                continue
            chain: List[str] = []
            key = tip
            while key:
                chain.append(key)
                key = self._chain_parent.get(key)
                if key is None or len(chain) > self.cache.n_blocks:
                    chain = []
                    break
            if not chain:
                continue
            chain.reverse()
            if len(self.cache.match_prefix(chain)) < len(chain):
                continue                 # partly aged out: not exportable
            store.put(chain, self.cache.export_keys(chain),
                      self.cache.wire_header())
            self._stored_tips.add(tip)
            wrote += 1
        return wrote

    def step(self) -> List[Completion]:
        """One engine iteration: join what fits, advance one prefill
        chunk (chunked mode), decode one token for every running
        sequence, evict what finished. Returns the completions this
        step produced."""
        results: List[Completion] = []
        self._join(results)
        self._advance_prefill(results)
        if self._running:
            self._decode()
            still = []
            for s in self._running:
                if s.remaining <= 0:
                    self._evict(s, results)
                else:
                    still.append(s)
            self._running = still
        # Demotion daemon (off unless a watermark armed it): above the
        # high watermark, demote one batch of cold cached-tier blocks
        # to host RAM — freeing device blocks BEFORE the next admission
        # needs them, at batch granularity so the device->host fetch
        # amortizes the link (ROOFLINE §12). demote() only ever takes
        # refcount-0 published blocks off the ref-aware LRU, so a live
        # sequence can never lose KV to the daemon.
        if self.demote_watermark > 0.0 and self.host_offload:
            used = self.cache.n_blocks - self.cache.free_blocks
            if used >= self.demote_watermark * self.cache.n_blocks:
                self.daemon_demotions += self.cache.demote(
                    self.demote_batch)
        self._steps += 1
        return results

    def run(self, max_steps: Optional[int] = None) -> List[Completion]:
        """Drive :meth:`step` until queue and batch drain (or
        ``max_steps``)."""
        out: List[Completion] = []
        while (self.queue_depth or self._running or self._prefilling) \
                and (max_steps is None or self._steps < max_steps):
            out.extend(self.step())
        return out

    # -- the sequential reference ------------------------------------------
    def full_prefill_logits(self, tokens: Sequence[int]) -> np.ndarray:
        """Sequential full-prefill reference: process ``tokens`` as ONE
        isolated prefill on a scratch pool (same jitted shape family,
        same ops) and return the real rows' f32 logits ``[len, vocab]``.
        The continuous-batching pin compares each request's streamed
        decode logits against rows of THIS, bit for bit."""
        t_real = len(tokens)
        if t_real > self.ctx_pad:
            raise ValueError(f"{t_real} tokens > engine context "
                             f"{self.ctx_pad}")
        t_pad = -(-t_real // self.q_block) * self.q_block
        toks = np.zeros((1, t_pad), np.int32)
        toks[0, :t_real] = list(tokens)
        positions = np.broadcast_to(
            np.arange(t_pad, dtype=np.int32)[None], (1, t_pad)).copy()
        # Contiguous scratch table on a zero pool of the SAME geometry,
        # so the jit cache is shared with live prefills (clipped: the
        # pool may hold fewer blocks than the context extent, and the
        # tail positions are masked anyway).
        tables = np.minimum(np.arange(self.nb_max, dtype=np.int32),
                            self.cache.n_blocks - 1)[None].copy()
        flat = np.full((1, t_pad), self.cache.oob_index, np.int32)
        bs = self.block_size
        for p in range(t_real):
            flat[0, p] = (p // bs) * bs + (p % bs)
        fn = self._fn(1, t_pad)
        scratch_k = jnp.zeros_like(self.cache.k)
        scratch_v = jnp.zeros_like(self.cache.v)
        args = (self.params, scratch_k, scratch_v, jnp.asarray(toks),
                jnp.asarray(positions), jnp.asarray(tables),
                jnp.asarray(flat))
        if self.mesh is not None:
            with mesh_context(self.mesh):
                logits, _, _ = fn(*args)
        else:
            logits, _, _ = fn(*args)
        return np.asarray(logits[0, :t_real], np.float32)

    # -- telemetry ---------------------------------------------------------
    def stats(self) -> Dict[str, float]:
        """The serve heartbeat triple (+ rates): qps, p50/p99 request
        latency, queue depth. Rates and percentiles cover the last
        ``stats_window_s`` only (bounded by engine age), so an idle
        replica's p99 decays to 0 and the autoscaler's scale-down gate
        can actually fire; ``completed``/``steps``/``forwards`` stay
        lifetime counters."""
        now = time.monotonic()
        with self._lock:
            events = list(self._events)
            tenant_blocks = dict(self._tenant_blocks)
            tenant_completed = dict(self._tenant_completed)
            tenant_queued: Dict[str, int] = {}
            for r, _ in self._queue:
                ten = getattr(r, "tenant", None)
                if ten is not None:
                    tenant_queued[ten] = tenant_queued.get(ten, 0) + 1
            rejections = self.admission_rejections
            prompt_hist = dict(self._prompt_hist)
        recent = [(l, n, ten) for t, l, n, ten in events
                  if now - t <= self.stats_window_s]
        lat = sorted(l for l, _, _ in recent)
        dt = max(1e-9, min(self.stats_window_s, now - self._t0))

        def _pct_of(vals: List[float], p: float) -> float:
            if not vals:
                return 0.0
            return vals[min(len(vals) - 1,
                            int(p * (len(vals) - 1) + 0.5))]

        def pct(p: float) -> float:
            return _pct_of(lat, p)

        # Per-tenant breakdown (tony_tpu.serve.qos): same window, same
        # percentile rule as the top-level numbers. Empty dict on an
        # untagged engine — the uniform-schema rule: every engine
        # flavor publishes the key, consumers never branch on kind.
        per_lat: Dict[str, List[float]] = {}
        per_tok: Dict[str, float] = {}
        for l, n, ten in recent:
            if ten is None:
                continue
            per_lat.setdefault(ten, []).append(l)
            per_tok[ten] = per_tok.get(ten, 0.0) + n
        tenants: Dict[str, Dict[str, float]] = {}
        for ten in (set(per_lat) | set(tenant_blocks)
                    | set(tenant_queued) | set(tenant_completed)):
            lats = sorted(per_lat.get(ten, []))
            tenants[ten] = {
                "qps": len(lats) / dt,
                "tokens_per_s": per_tok.get(ten, 0.0) / dt,
                "p99_ms": 1e3 * _pct_of(lats, 0.99),
                "queued": float(tenant_queued.get(ten, 0)),
                "blocks": float(tenant_blocks.get(ten, 0)),
                "completed": float(tenant_completed.get(ten, 0)),
            }

        stats = {
            "qps": len(recent) / dt,
            "tokens_per_s": sum(n for _, n, _ in recent) / dt,
            "p50_ms": 1e3 * pct(0.50),
            "p99_ms": 1e3 * pct(0.99),
            "queue_depth": float(self.queue_depth),
            "running": float(self.running),
            "completed": float(self._completed),
            "steps": float(self._steps),
            "forwards": float(self.forwards),
            # Effective throughput for the autoscaler: generated tokens
            # per TARGET forward launch (lifetime), counted at EMIT time
            # so a replica mid-way through long generations reports what
            # it is actually producing, not zero until first completion.
            # Raw forward counts undercount a speculative replica's real
            # throughput — ScalingPolicy's decision matrix is unchanged,
            # but the heartbeat now carries the honest number (the
            # speculative lane also reports its acceptance rate; 0.0
            # here).
            "tokens_per_forward": (self._emitted / self.forwards
                                   if self.forwards else 0.0),
            "acceptance_rate": 0.0,
            # Prefix-cache / chunked-prefill telemetry (PR 13): zeros
            # when the features are off — every engine flavor publishes
            # the same schema, so the fleet's heartbeat consumers
            # (router, autoscaler, portal) never branch on engine kind.
            "prefix_cache_hit_rate": (
                self.prefix_hit_blocks / self.prefix_lookup_blocks
                if self.prefix_lookup_blocks else 0.0),
            "blocks_shared": float(self.cache.adopted_total),
            "prefill_chunks": float(self.prefill_chunks),
            # Disaggregated-serving telemetry (PR 15): the replica's
            # role rides as a STRING (the schema's second non-scalar
            # next to prefix_digest — normalize_serve_telemetry passes
            # it through), the handoff counters as zeros on colocated
            # engines so the fleet schema stays uniform and the router/
            # autoscaler never branch on engine kind.
            "role": self.role,
            "blocks_shipped": float(self.blocks_shipped),
            "handoff_ms": float(self.handoff_ms),
            "imports_failed": float(self.imports_failed),
            # KV memory hierarchy telemetry (PR 16): zeros on engines
            # without the host tier, so the fleet schema stays uniform
            # (same rule as every widening above). park_hit_rate is the
            # fraction of conversation-tagged admissions that resumed
            # from parked KV instead of re-prefilling.
            "host_blocks": float(self.cache.host_blocks_used),
            "parked_seqs": float(len(self._parked)),
            "demotions": float(self.cache.demoted_total),
            "promotions": float(self.cache.promoted_total),
            "park_hit_rate": (self.park_hits / self.park_lookups
                              if self.park_lookups else 0.0),
            # Cold-start plane telemetry (PR 17): zeros on engines
            # without the AOT cache / warm pool, same uniform-schema
            # rule as every widening above. warm_standby rides the
            # heartbeat so the session excludes standbys from routing
            # and the autoscaler from the active count until the AM
            # promotes them.
            "aot_hits": float(self.aot_hits),
            "aot_misses": float(self.aot_misses),
            "compile_ms": float(self.compile_ms),
            "warm_standby": 1.0 if self.warm_standby else 0.0,
            "daemon_demotions": float(self.daemon_demotions),
            # Multi-tenant QoS telemetry (PR 18): zeros / empty dict on
            # untagged engines — the uniform-schema rule again. The
            # tenants dict is the ONE nested value the heartbeat schema
            # carries (normalize_serve_telemetry normalizes one level
            # of dict-of-scalars); the history plane's SLO dashboards
            # and the per-tenant billing rollups both read it.
            "admission_rejections": float(rejections),
            "qos_deferrals": float(self.qos_deferrals),
            "tenants": tenants,
            # Continuous-publication telemetry (PR 20): which weight
            # version this replica is serving, and whether it is inside
            # a swap window right now. weight_version rides the
            # heartbeat so the AM's rolling fleet swap can tell who
            # still needs the new manifest; swapping=1.0 is the
            # router's down-mark signal (refresh_from_task_infos
            # retires the replica for the window, the next clean beat
            # revives it). prompt_hist is the padded-prefill-length
            # histogram warm() self-tunes from — dict of str(pad) →
            # count, the same one-level dict-of-scalars shape the
            # tenants dict established, so normalize_serve_telemetry
            # passes it through unchanged. All zeros / empty on an
            # unswapped engine: the uniform-schema rule.
            "weight_version": float(self.weight_version),
            "weight_step": float(self.weight_step),
            "weight_swaps": float(self.weight_swaps),
            "swapping": 1.0 if self.swapping else 0.0,
            "prompt_hist": {str(k): float(v)
                            for k, v in prompt_hist.items()},
        }
        stats.update(self._extra_stats())
        _record(f"{self.tag}_stats", **stats)
        return stats

    def _extra_stats(self) -> Dict[str, float]:
        """Subclass hook (tony_tpu.serve.spec overrides): extra fields
        merged into :meth:`stats` before it is recorded/published."""
        return {}

    def prefix_digest(self, limit: int = 256) -> List[str]:
        """The replica's block-content advertisement: the most recently
        published chain keys. Rides the stats file → heartbeat → session
        so the router can score cache overlap without asking the
        replica; empty when prefix caching is off."""
        if not self.prefix_cache:
            return []
        return self.cache.digest(limit)

    def parked_digest(self, limit: int = 256) -> List[str]:
        """The replica's parked-conversation advertisement: the
        conversation handles whose KV this engine holds in its host
        tier. Rides the heartbeat next to the prefix digest so the
        router re-pins a returning turn to the replica that can resume
        it without a re-prefill; empty without the tier."""
        return [str(c) for c in list(self._parked)[-limit:]]

    def write_stats(self, path: str,
                    extra: Optional[Dict[str, Any]] = None) -> None:
        """Atomically publish :meth:`stats` as JSON — the file the
        executor's heartbeat loop piggybacks to the AM (jax-free on the
        reader side). The payload adds the prefix digest (a list — the
        one non-scalar the heartbeat schema carries) and any caller
        ``extra`` (the replica adds its RPC port so the router can dial
        it)."""
        payload: Dict[str, Any] = dict(self.stats())
        digest = self.prefix_digest()
        if digest:
            payload["prefix_digest"] = digest
        parked = self.parked_digest()
        if parked:
            payload["parked_digest"] = parked
        if extra:
            payload.update(extra)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, path)

    # -- warm-standby promotion (tony_tpu.serve.scaling) -------------------
    def promote(self) -> bool:
        """Leave warm standby: the AM's scale-up path calls this (over
        the replica's ``promote`` RPC verb) instead of cold-granting a
        container — the next stats publish advertises warm_standby=0
        and the session adds the replica to the routable endpoint set.
        Returns whether the engine WAS a standby (idempotent: promoting
        an active replica is a no-op, so a duplicated RPC is
        harmless)."""
        with self._lock:
            was = self.warm_standby
            self.warm_standby = False
        return was

    # -- hot weight swap (tony_tpu.serve.swap) -----------------------------
    def swap_params(self, new_params: Any, *, version: int,
                    step: int) -> None:
        """The serve engine's hot swap: the base flip (geometry-checked,
        atomic-or-rolled-back, zero recompiles) plus the KV hygiene the
        bitwise contract needs — every published prefix block and every
        demoted host stem holds rows COMPUTED UNDER THE OLD WEIGHTS, so
        a post-swap admission adopting them would stream a mixed-version
        answer. The device index and the host stem tier flush (the rows
        recompute fresh, bit-identical to a fresh replica restored from
        the same manifest); parked CONVERSATIONS survive — their records
        are an explicit continuity contract (the resumed turn keeps its
        pre-swap history's KV, the documented tradeoff the re-published
        parked digest advertises)."""
        super().swap_params(new_params, version=version, step=step)
        self.cache.flush_prefix()
        # Stem-export bookkeeping refers to the flushed keys — a
        # post-swap export must only ever name new-weight chains.
        self._chain_parent.clear()
        self._hot_tips.clear()
        self._stored_tips.clear()

    # -- static-analysis hook ---------------------------------------------
    def decode_traced(self, batch: Optional[int] = None):
        """``(jitted, example_args)`` of the canonical decode bucket for
        :func:`tony_tpu.analysis.analyze_serve_step` — the same jit the
        loop runs, traced, never executed. Always the raw jitted
        Wrapped from ``_fns`` — the AOT cache resolves executables in a
        parallel dict precisely so this hook (and its signature pin)
        cannot drift when the cache is armed."""
        b = _bucket_of(self.decode_buckets,
                       batch if batch is not None else 1)
        return self._fn(b, self.q_block), \
            self._example_args(b, self.q_block)

    def prefill_traced(self):
        """``(jitted, example_args)`` of the canonical prefill-chunk
        launch for ``tony analyze --config route`` — the ``(1, chunk)``
        shape every non-final chunk of a chunked prefill rides (the
        monolithic q_block row block when chunking is off). Same
        builder, same rule suite as decode: zero inter-chip collectives
        on the replica mesh, donated KV pools, pinned signature — the
        chunk geometry is the ONLY compiled prefill shape the feature
        declares."""
        t = int(self.prefill_chunk or self.q_block)
        return self._fn(1, t), self._example_args(1, t)


class EngineFront:
    """Thread-safe request front over ONE shared engine: each caller
    submits and then takes turns advancing the loop until its own
    completion lands, so overlapping calls ride one continuous batch.

    Factored out of the replica (which fronts it over RPC) so the
    router's in-process transport, the bench's multi-replica drive, and
    :class:`tony_tpu.serve.replica.Replica` all run the IDENTICAL drive
    discipline — the router tests compare routed against unrouted
    serving through the same loop."""

    def __init__(self, engine: ServeEngine):
        self.engine = engine
        self._drive = threading.Lock()
        self._done: Dict[Any, Completion] = {}
        self._rid = 0
        # Minted rids cross replicas since the disaggregated handoff (a
        # prefill front's rid lands on a decode engine that also mints
        # its own), so a bare counter would collide routinely — every
        # front mints in its own namespace.
        self._rid_ns = uuid.uuid4().hex[:8]
        self._rid_lock = threading.Lock()

    def fresh_rid(self) -> str:
        with self._rid_lock:
            self._rid += 1
            return f"req-{self._rid_ns}-{self._rid}"

    def generate(self, tokens: Sequence[int], max_new_tokens: int,
                 rid: Optional[Any] = None,
                 conv: Optional[Any] = None,
                 tenant: Optional[str] = None) -> Completion:
        """Submit one request and drive the shared engine until it
        completes. ``conv`` tags the request with its conversation
        handle so a host-tier engine parks/resumes it across turns;
        ``tenant`` names its QoS class on a budget-armed engine."""
        if rid is None:
            rid = self.fresh_rid()
        self.engine.submit(Request(rid=rid, tokens=list(tokens),
                                   max_new_tokens=int(max_new_tokens),
                                   conv=conv, tenant=tenant))
        return self._drive_until(rid)

    def _drive_until(self, rid: Any) -> Completion:
        """Take turns advancing the shared loop until ``rid``'s
        completion lands — the one drive discipline ``generate`` and
        the disaggregated receiver (``serve.disagg.DecodeFront``, whose
        handoff admissions join the same continuous batch) share."""
        while True:
            with self._drive:
                if rid in self._done:
                    return self._done.pop(rid)
                for c in self.engine.step():
                    self._done[c.rid] = c
            # Another thread may own the completion we need next round;
            # yield so it can collect.
            time.sleep(0)

    def quiesce_and_swap(self, fn: Callable[[], None]) -> None:
        """Drain the engine to an iteration boundary and run ``fn`` (the
        weight flip) there, without dropping a request. Under the drive
        lock: set ``engine.swapping`` (the ``_join`` gate — queued
        requests stay queued), step the engine until every in-flight
        sequence completes under the OLD weights (completions stash into
        ``_done`` exactly as a caller's own drive turn would, so
        concurrent ``_drive_until`` threads blocked on the lock collect
        them the moment we release), call ``fn`` at the drained
        boundary, then clear the gate — the queued backlog admits on the
        next step under the NEW weights. No request ever spans weight
        versions; none is dropped. A failed flip propagates after the
        gate clears: the engine keeps serving the old weights."""
        with self._drive:
            self.engine.swapping = True
            try:
                while self.engine._running or self.engine._prefilling:
                    for c in self.engine.step():
                        self._done[c.rid] = c
                fn()
            finally:
                self.engine.swapping = False
