"""Disaggregated prefill/decode: split-gang serving with KV-block
handoff over the RPC wire.

PR 13 made the fleet the unit of throughput, but prefill and decode
still shared a replica: a prefill burst and the decode floor contend
for the same chips, and chunked prefill (BENCH_r14) is a mitigation,
not an isolation. This module splits the two phases onto separate
replica ROLES — Arax's framing (PAPERS 2305.01291: workloads decoupled
from concrete accelerator instances) taken one phase deeper than the
router already did:

* a **prefill replica** runs the prompt through the existing chunked
  ``(1, chunk)`` launch family and emits the FIRST token — its output
  is KV + one token, never a generation loop
  (:meth:`~tony_tpu.serve.engine.ServeEngine.prefill_only`);
* the sequence's KV blocks ship over the wire — the paged pool's flat
  block payloads plus the prefix chain-hash keys ARE the wire format
  (:meth:`~tony_tpu.serve.kvcache.PagedKVCache.export_blocks`, per-block
  CRC32 reusing the ckpt plane's chunk-checksum idiom);
* a **decode replica** imports them into its OWN pool
  (:meth:`~tony_tpu.serve.kvcache.PagedKVCache.import_blocks` —
  AdmissionError-typed, state-unchanged on failure, composing with the
  prefix tier so a shipped shared-prefix stem is ADOPTED, not
  re-transferred: the shipper first ``kv_offer``-s the chain keys and
  ships only the blocks past the receiver's match) and continues the
  generation on its continuous batch.

Bitwise contract: the imported bytes are exactly the bytes the prefill
wrote (device → host → wire → host → device round-trips the pool dtype
losslessly, CRC-gated), and every serve op is row-independent at
tile-multiple shapes — so the disaggregated token stream AND per-token
logits are pinned BITWISE against the colocated PR 10/12/13 engine
(tests/test_disagg.py), spec lane riding on the decode side included.

Failure semantics (the one-slow-importer-must-never-wedge-the-prefill-
gang contract): a decode pool under pressure rejects the import with
the cache untouched; :class:`KVShipper` retries with bounded backoff
and surfaces a typed :class:`HandoffError` when the budget is spent —
the router then re-dispatches or falls back to COLOCATED prefill on the
decode replica (its engine prefills for itself), keeping the PR 13
OSError-vs-request-error failover split intact.

Jax-free on purpose (the same layering rule as ``serve.router`` /
``serve.prefix``): the router imports :class:`HandoffError` for its
fallback logic on a gateway host with no accelerator stack, and the
fronts only *hold* an engine-backed :class:`~tony_tpu.serve.engine.
EngineFront` — nothing here imports jax at module level.

Threading contract: every pool mutation the handoff path performs —
the prefill-side export and the decode-side import, both arriving on
RPC receiver threads — happens under the owning front's drive lock,
the same lock that serializes ``generate`` callers onto the engine
loop. The PR 14 concurrency plane (lock-discipline lint + lock-order
witness) gates this module, and the threaded kvcache interleave in
tests/test_concurrency.py drives export/import from N threads with the
refcount/free/LRU partition pinned at every quiescent point.
"""

from __future__ import annotations

import base64
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


class HandoffError(RuntimeError):
    """The KV handoff cannot complete: a CRC/geometry mismatch on the
    wire payload, an offered prefix that evaporated before import, or a
    shipping budget spent against a decode pool under pressure.
    ``retryable`` mirrors :class:`~tony_tpu.serve.kvcache.
    AdmissionError`'s flag; ``matched`` (when set) is the receiver's
    CURRENT prefix-match count so a retry re-ships exactly the missing
    tail instead of starting a fresh offer round."""

    def __init__(self, message: str, *, retryable: bool = True,
                 matched: Optional[int] = None):
        super().__init__(message)
        self.retryable = retryable
        self.matched = matched


def encode_f32(row: np.ndarray) -> str:
    """Wire form of one f32 logits row (the prefill-side first-token
    row a ``keep_logits`` engine ships so the decode side's Completion
    carries every per-token row — the bitwise pin surface)."""
    return base64.b64encode(
        np.ascontiguousarray(row, np.float32).tobytes()).decode("ascii")


def decode_f32(data: str) -> np.ndarray:
    return np.frombuffer(base64.b64decode(data), np.float32).copy()


def _classify(exc: Exception) -> tuple:
    """``(retryable, matched)`` of one shipping failure. Typed errors
    carry their own flags; wire errors (an RpcError string from the
    decode replica) are recognized by the transported type prefix —
    the JSON-lines RPC wraps application errors as
    ``"<TypeName>: <message>"`` — and treated as retryable: the retry
    budget is bounded either way, and a genuinely-never-fits request
    fails identically on the colocated fallback."""
    if isinstance(exc, HandoffError):
        return exc.retryable, exc.matched
    retryable = getattr(exc, "retryable", None)
    if retryable is not None:           # AdmissionError without the import
        return bool(retryable), None
    msg = str(exc)
    if msg.startswith(("AdmissionError:", "HandoffError:")):
        return True, None
    if isinstance(exc, OSError):
        # Transport fault mid-handoff: the import may or may not have
        # landed; re-offer from scratch (idempotent — a landed import
        # makes the retry's fresh-admission check fail loudly).
        return True, None
    return False, None


class KVShipper:
    """The prefill-side half of the handoff protocol: offer the chain
    keys, ship only the unmatched block tail, retry with bounded
    backoff, and surface a typed :class:`HandoffError` when the budget
    is spent — the shipper never blocks unboundedly, so one slow
    importer cannot wedge the prefill gang (its engine already freed
    the sequence's blocks before shipping begins)."""

    def __init__(self, *, max_attempts: int = 3, backoff_s: float = 0.05):
        if max_attempts < 1:
            raise ValueError(f"need max_attempts >= 1, got {max_attempts}")
        self.max_attempts = int(max_attempts)
        self.backoff_s = float(backoff_s)

    def ship(self, handoff: Dict[str, Any], decode: Any) -> tuple:
        """Offer/import ``handoff`` against ``decode`` (anything with
        ``kv_offer(keys=...) -> int`` and ``kv_import(payload=...)``,
        an in-process :class:`DecodeFront` or an RPC dial). Returns
        ``(completion, shipped_blocks)`` — the decode side's completion
        (it drives its engine until the resumed generation finishes)
        and the block count that actually crossed the wire. Returned,
        not stashed on ``self``: one shipper serves CONCURRENT
        ``prefill_handoff`` callers (the replica RPC server is
        threaded), and shared mutable per-ship state would tear.

        Known edge: a transport fault AFTER the decode side committed
        the import leaves that sequence decoding on the receiver — the
        retry's rid-collision check rejects typed, the router falls
        back colocated, and the orphaned generation completes on the
        receiver's own handler thread and is dropped there: bounded
        duplicated decode work per incident, never a wedge, a leak, or
        a wrong answer."""
        keys: List[str] = list(handoff.get("keys") or ())
        blocks = list(handoff.get("blocks") or ())
        offset: Optional[int] = None
        last: Optional[Exception] = None
        attempts = 0
        for attempt in range(self.max_attempts):
            attempts = attempt + 1
            if attempt:
                time.sleep(self.backoff_s * (2 ** (attempt - 1)))
            try:
                if offset is None:
                    offset = min(max(0, int(decode.kv_offer(keys=keys))),
                                 len(blocks))
                payload = dict(handoff, offset=offset,
                               blocks=blocks[offset:])
                out = decode.kv_import(payload=payload)
                return out, len(blocks) - offset
            except Exception as e:  # noqa: BLE001 — classified below
                last = e
                retryable, matched = _classify(e)
                if not retryable:
                    break
                # A stale offer re-ships the now-missing tail; anything
                # else re-offers from scratch.
                offset = matched if matched is not None \
                    and not isinstance(e, OSError) else None
        raise HandoffError(
            f"KV handoff failed after {attempts} attempt(s): "
            f"{last}", retryable=False) from last


class DecodeFront:
    """The decode replica's receiver half over one shared
    :class:`~tony_tpu.serve.engine.EngineFront`: ``kv_offer`` answers
    the shipper's prefix probe, ``kv_import`` admits the shipped
    sequence into the engine and drives the shared loop until its
    generation completes (exactly the ``generate`` discipline —
    overlapping handoffs and colocated requests ride one continuous
    batch). Every cache mutation happens under the front's drive lock:
    the import arrives on an RPC receiver thread while another thread
    drives decode, and the paged pool is only safe under one driver —
    the contract the concurrency plane audits."""

    def __init__(self, front: Any):
        self.front = front

    def kv_offer(self, keys: Sequence[str]) -> int:
        with self.front._drive:
            return len(self.front.engine.cache.match_prefix(
                [str(k) for k in keys]))

    def kv_import(self, payload: Dict[str, Any]) -> Any:
        with self.front._drive:
            rid, done = self.front.engine.admit_handoff(payload)
        if done is not None:
            return done
        return self.front._drive_until(rid)

    def generate(self, tokens: Sequence[int], max_new_tokens: int,
                 rid: Optional[Any] = None,
                 conv: Optional[Any] = None,
                 tenant: Optional[str] = None) -> Any:
        """The colocated fallback path (the decode engine prefills for
        itself when a handoff could not be placed)."""
        return self.front.generate(tokens, max_new_tokens, rid=rid,
                                   conv=conv, tenant=tenant)


class PrefillFront:
    """The prefill replica's shipper half over one shared
    :class:`~tony_tpu.serve.engine.EngineFront`: run the prefill-only
    engine mode under the drive lock, then ship the exported KV to the
    decode target OUTSIDE it — the prefill engine is free for the next
    prompt the moment its blocks are exported, whatever the importer
    does. ``decode`` is an in-process :class:`DecodeFront` or a
    ``host:port`` address (dialed over the control-plane RPC)."""

    def __init__(self, front: Any, *, shipper: Optional[KVShipper] = None,
                 dial_timeout_s: float = 15.0):
        self.front = front
        self.shipper = shipper or KVShipper()
        self.dial_timeout_s = float(dial_timeout_s)

    def prefill_handoff(self, tokens: Sequence[int], max_new_tokens: int,
                        rid: Optional[Any] = None,
                        decode: Any = None,
                        conv: Optional[Any] = None,
                        tenant: Optional[str] = None) -> Any:
        if decode is None:
            raise ValueError("prefill_handoff needs a decode target "
                             "(a DecodeFront or a host:port address)")
        if isinstance(decode, str):
            decode = _dial_decode(decode, self.dial_timeout_s)
        from tony_tpu.serve.engine import Request

        if rid is None:
            rid = self.front.fresh_rid()
        from tony_tpu.serve.kvcache import AdmissionError

        eng = self.front.engine
        with self.front._drive:
            try:
                handoff = eng.prefill_only(Request(
                    rid=rid, tokens=[int(t) for t in tokens],
                    max_new_tokens=int(max_new_tokens), conv=conv,
                    tenant=tenant))
            except AdmissionError as e:
                if not getattr(e, "retryable", True):
                    raise               # never fits: same as colocated submit
                # Transient PREFILL-pool pressure: a colocated engine
                # absorbs this by leaving the request queued, but
                # prefill_only has no queue to park it in — re-type as
                # a non-retryable HandoffError so the router's fallback
                # runs colocated prefill on the decode replica instead
                # of hard-failing a request the colocated path would
                # have served.
                raise HandoffError(
                    f"prefill pool pressure for {rid!r}: {e}",
                    retryable=False) from e
        # Counters bank on the ENGINE (its stats() is the fleet's one
        # telemetry surface) through a locked helper: concurrent
        # prefill_handoff callers on the threaded RPC front would tear
        # a bare `+=`. Failed ships bank nothing here — the importer's
        # rejection is visible as the DECODE side's imports_failed, and
        # the raised HandoffError carries the attempt ledger. The
        # engines' handoff_ms accrues inside prefill_only/admit_handoff
        # (export/import wall — NOT the shipped sequence's downstream
        # generation, which ship() blocks on).
        out, shipped = self.shipper.ship(handoff, decode)
        eng.note_handoff_shipped(shipped)
        return out

    def generate(self, tokens: Sequence[int], max_new_tokens: int,
                 rid: Optional[Any] = None,
                 conv: Optional[Any] = None,
                 tenant: Optional[str] = None) -> Any:
        return self.front.generate(tokens, max_new_tokens, rid=rid,
                                   conv=conv, tenant=tenant)


def _dial_decode(address: str, timeout: float) -> Any:
    """RPC transport to a decode replica's receiver verbs (lazy import,
    like the router's ``_rpc_dial`` — the RPC stack only loads when a
    network decode target is actually dialed)."""
    from tony_tpu.rpc import RpcClient

    class _Decode:
        def kv_offer(self, keys):
            with RpcClient(address, timeout=timeout) as client:
                return client.call("kv_offer", keys=list(keys))

        def kv_import(self, payload):
            with RpcClient(address, timeout=timeout) as client:
                return client.call("kv_import", payload=payload)

        def generate(self, tokens, max_new_tokens, rid=None, conv=None,
                     tenant=None):
            with RpcClient(address, timeout=timeout) as client:
                return client.call("generate", tokens=list(tokens),
                                   max_new_tokens=int(max_new_tokens),
                                   rid=rid, conv=conv, tenant=tenant)

    return _Decode()
