"""Hot weight swap: serve fleets follow the train gang's publications.

The other half of :mod:`tony_tpu.publish` — the train gang stages a
versioned pointer file over its committed checkpoints; this module is
everything the SERVE side needs to roll onto it without dropping a
request or burning a container:

* :class:`SwapError` — the typed atomic-or-rolled-back failure. A swap
  that raises it left the engine serving the OLD weights whole; the
  caller retries or gives up, the replica never serves a mix.
* :func:`resolve_target` — pointer → (version, step): which committed
  manifest a swap should restore. Shared by the replica's ``swap`` RPC
  verb and the AM's publication tick, so both sides agree on the target
  by construction.
* :class:`FleetSwapController` — the AM's rolling-swap pacing: ONE
  replica in flight at a time (warm standbys first — they cover the
  routed gap — then actives by index), a per-replica wall-clock
  timeout, and a cooldown after a failure so a poisoned manifest does
  not hammer the fleet. Pure decision logic over an injected clock:
  unit-testable without an AM, a replica, or jax.
* :func:`derive_prefill_pads` — warm()'s pad self-tuner: read the
  prompt-length histogram the engines publish into the SERVE_WINDOW
  event records and return the pads worth precompiling, replacing the
  caller-named ``prefill_pads=`` guess with what the traffic actually
  looked like.

The swap itself happens in :meth:`tony_tpu.serve.replica.Replica.
hot_swap` (restore OUTSIDE the drive lock, flip inside
``EngineFront.quiesce_and_swap`` at a drained iteration boundary) and
:meth:`tony_tpu.serve.engine.ServeEngine.swap_params` (geometry-checked
reference store + prefix/host-stem flush). This module stays jax-free
at import — it is control-plane code the AM runs.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from tony_tpu.ckpt.format import committed_steps
from tony_tpu.publish import latest_publication


class SwapError(RuntimeError):
    """A hot swap that could not commit. The contract every raiser
    honors: the engine still holds the OLD params, whole — geometry
    mismatch, missing manifest, and restore failures all roll back to
    exactly the weights that were serving before the attempt."""


def resolve_target(ckpt_dir: str, *, version: Optional[int] = None,
                   step: Optional[int] = None) -> Tuple[int, int]:
    """What a swap should restore: ``(version, step)``.

    Default is the published pointer (:func:`latest_publication`); an
    explicit ``step`` overrides it (an operator pinning a roll-back
    target) and mints version 0 when no pointer names it. ``version``
    asserts the pointer still carries the version the caller saw — a
    publication racing past it is a :class:`SwapError`, not a silent
    swap onto weights nobody asked for."""
    rec = latest_publication(ckpt_dir)
    if step is not None:
        step = int(step)
        if step not in committed_steps(ckpt_dir):
            raise SwapError(f"step {step} has no committed manifest "
                            f"under {ckpt_dir}")
        if rec is not None and rec["step"] == step:
            return rec["version"], step
        return 0, step
    if rec is None:
        raise SwapError(f"no publication under {ckpt_dir} — nothing to "
                        f"swap to (run `tony publish` or arm "
                        f"publish_every on the train loop)")
    if version is not None and rec["version"] != int(version):
        raise SwapError(f"publication moved: wanted version {version}, "
                        f"pointer now names {rec['version']}")
    return rec["version"], rec["step"]


class FleetSwapController:
    """Rolling-swap pacing for one serve fleet.

    The AM's publication tick drives it: :meth:`set_target` when a new
    publication shows up on the heartbeat, :meth:`next_replica` each
    tick to learn who (if anyone) to swap now, :meth:`begin` /
    :meth:`finish` around the actual RPC (which the AM runs on a
    daemon thread — the tick never blocks on a restore). Invariants:

    * at most ONE replica in flight — the router down-marks the
      swapping replica, and the rest of the fleet must carry its
      traffic, so a second concurrent swap would halve capacity;
    * warm standbys swap FIRST (they serve no traffic — free dry runs
      that validate the manifest before any active risks its window),
      then actives in index order;
    * a failure opens a ``cooldown_s`` window before the next attempt,
      and :meth:`check_timeout` reaps an attempt whose thread wedged
      past ``timeout_s`` so the fleet is never stuck behind one hung
      restore.

    ``swap_fn`` is injected — ``(replica_id) -> None``, raising on
    failure — so tests drive the whole policy with a stub fleet and no
    jax."""

    def __init__(self, swap_fn: Optional[Callable[[Any], None]] = None, *,
                 timeout_s: float = 120.0, cooldown_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        # Optional: the AM drives begin()/finish() around its own RPC
        # thread; run() needs it.
        self.swap_fn = swap_fn
        self.timeout_s = float(timeout_s)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self.target: Optional[Tuple[int, int]] = None
        self.in_flight: Optional[Any] = None
        self.swapped = 0
        self.failed = 0
        self._started = 0.0
        self._cooldown_until = 0.0

    def set_target(self, version: int, step: int) -> bool:
        """Adopt a publication as the fleet's swap target. Returns True
        the first time this (strictly newer) version is seen — the
        AM emits its one PUBLISH event on that edge."""
        version, step = int(version), int(step)
        if self.target is not None and version <= self.target[0]:
            return False
        self.target = (version, step)
        # A new target clears a stale failure cooldown: the operator
        # may have published a FIX for whatever the last attempt hit.
        self._cooldown_until = 0.0
        return True

    def next_replica(self, fleet: Iterable[Dict[str, Any]]) -> Optional[Any]:
        """Who to swap now, or None. ``fleet`` rows carry ``id``,
        ``version`` (what the replica's heartbeat says it serves),
        ``standby`` and ``index``; rows already at the target version
        need nothing."""
        if self.target is None or self.in_flight is not None:
            return None
        if self.clock() < self._cooldown_until:
            return None
        want = self.target[0]
        behind = [r for r in fleet if int(r.get("version", 0)) < want]
        if not behind:
            return None
        behind.sort(key=lambda r: (not bool(r.get("standby")),
                                   int(r.get("index", 0))))
        return behind[0]["id"]

    def begin(self, replica_id: Any) -> None:
        self.in_flight = replica_id
        self._started = self.clock()

    def finish(self, replica_id: Any, ok: bool) -> None:
        """Record one attempt's outcome (idempotent against a reaped
        timeout racing the thread's own late finish)."""
        if self.in_flight != replica_id:
            return
        self.in_flight = None
        if ok:
            self.swapped += 1
        else:
            self.failed += 1
            self._cooldown_until = self.clock() + self.cooldown_s

    def check_timeout(self) -> Optional[Any]:
        """Reap an in-flight attempt past ``timeout_s``: returns the
        wedged replica id (the AM records ok=False for it) or None."""
        if self.in_flight is None \
                or self.clock() - self._started <= self.timeout_s:
            return None
        rid, self.in_flight = self.in_flight, None
        self.failed += 1
        self._cooldown_until = self.clock() + self.cooldown_s
        return rid

    def run(self, replica_id: Any) -> Tuple[bool, str, float]:
        """One attempt, synchronously: begin → ``swap_fn`` → finish.
        Returns ``(ok, detail, wall_s)`` — what the SWAP event records.
        The AM calls this on a named daemon thread; tests call it
        inline."""
        if self.swap_fn is None:
            raise ValueError("FleetSwapController.run needs a swap_fn")
        self.begin(replica_id)
        t0 = self.clock()
        try:
            self.swap_fn(replica_id)
        except Exception as exc:   # noqa: BLE001 — every failure rolls back
            self.finish(replica_id, False)
            return False, f"{type(exc).__name__}: {exc}", self.clock() - t0
        self.finish(replica_id, True)
        return True, "", self.clock() - t0


def derive_prefill_pads(records: Iterable[Dict[str, Any]], *,
                        q_block: int = 16, ctx_max: Optional[int] = None,
                        limit: int = 4) -> List[int]:
    """warm()'s pad self-tuner: the prefill pads worth precompiling,
    read from the prompt-length histograms the fleet's engines publish
    (``prompt_hist`` in every stats window, accumulated into
    SERVE_WINDOW event records). Sums counts across every record,
    keeps the ``limit`` most-frequent pads, returns them ascending —
    feed straight to ``engine.warm(prefill_pads=...)``. Pads that are
    not multiples of ``q_block`` or exceed ``ctx_max`` are skipped
    (stale histograms from a differently-padded fleet must not warm
    programs this engine can never launch). Empty in, empty out: the
    caller falls back to warming the decode family alone."""
    counts: Dict[int, float] = {}
    for rec in records:
        stats = rec.get("payload", rec).get("stats", rec.get("payload", rec))
        hist = stats.get("prompt_hist") if isinstance(stats, dict) else None
        if not isinstance(hist, dict):
            continue
        for k, v in hist.items():
            try:
                pad, n = int(k), float(v)
            except (TypeError, ValueError):
                continue
            if pad <= 0 or pad % q_block:
                continue
            if ctx_max is not None and pad > ctx_max:
                continue
            counts[pad] = counts.get(pad, 0.0) + n
    top = sorted(counts, key=lambda p: (-counts[p], p))[:max(0, int(limit))]
    return sorted(top)
