"""Paged KV cache: a fixed-size block pool with per-sequence block tables.

The pool is two device arrays ``[n_layers, n_blocks, block_size, kv_dim]``
(k and v); a sequence owns an ordered list of block ids (its *block
table*) covering positions ``[0, len)`` — position ``p`` lives at row
``p % block_size`` of block ``table[p // block_size]``. Allocation is
host-side bookkeeping only (a free list of ids); the device arrays are
written by the engine's jitted step through flat scatter indices the
allocator hands out. Blocks are NOT zeroed on free/realloc: every
position is written before any query can attend it (the flash-decode
mask admits key ``j`` only for rows at position ``>= j``), so stale
bytes are provably unread — and the reuse test pins that.

Prefix sharing (PR 13) makes the pool CONTENT-ADDRESSED at block
granularity: blocks are refcounted, and a full block whose positions
are all verified-written can be *published* under its chain hash
(:mod:`tony_tpu.serve.prefix` — the key covers the whole token prefix,
because a KV row depends on every earlier token). Admission of a
request whose prompt chain-matches published blocks *adopts* them
(refcount++) instead of recomputing the prefill; the adopted bytes are
bit-identical to what the prefill would have written (row independence
at tile multiples — the serve plane's core numerics contract), so
sharing cannot change an output bit. Writes go through
:meth:`write_index`, which COPIES-ON-WRITE: a block with refcount > 1
is never mutated — the writer gets a private device copy first. Freed
blocks that carry a hash retire to an LRU *cached tier* instead of the
LIFO free list: still addressable (a recently-evicted conversation's
prefix revives on the next turn), reclaimed ref-aware LRU only when
the LIFO tier runs dry.

Speculative decoding (tony_tpu.serve.spec) adds a second, revocable
allocation tier on top: :meth:`~PagedKVCache.spec_reserve` grows a
table to cover drafted-but-unverified positions, :meth:`commit`
advances the per-sequence *write cursor* to the accepted length
(promoting the blocks that cover it), and :meth:`rollback` truncates
the block table back to the committed extent, returning the rejected
extension to the free list in reverse order — so the LIFO reuse
contract holds for speculation too. Speculative extension blocks are
always FRESH (never adopted, never published while revocable), so a
rollback can never strand a shared block: it returns exactly the
private extension, and an adopted prefix below the cursor is untouched.

Disaggregated serving (tony_tpu.serve.disagg) adds the wire tier:
:meth:`~PagedKVCache.export_blocks` snapshots a sequence's blocks as
CRC32-guarded payloads (the ckpt plane's chunk-checksum idiom) and
:meth:`~PagedKVCache.import_blocks` is the receiving admission path —
atomic like :meth:`~PagedKVCache.admit_shared` and composing with the
prefix tier, so a shipped shared-prefix stem that the importer already
holds is adopted instead of re-written.

The host-offload tier (PR 16) turns the pool into a THREE-tier memory
hierarchy: ``host_blocks > 0`` arms a host-RAM tier holding the same
CRC-guarded wire payloads the handoff ships, so everything that moves
between device and host re-enters through the import path's
verify-then-commit discipline — a corrupt host byte can never reach
the pool. Two populations live there:

* **demoted stems** — when the LIFO tier runs dry, the cached tier's
  LRU eviction spills the victim's content to host instead of dropping
  it (the existing ref-aware LRU order IS the demotion policy);
  :meth:`~PagedKVCache.promote` re-stages a chain-key run back into
  device blocks (one batched scatter) where the device index stops
  matching, placing them refcount-0 in the cached tier so the
  admission that follows adopts them like any published block;
* **parked sequences** — :meth:`~PagedKVCache.park` snapshots a
  sequence's blocks (ONE batched device fetch per pool) plus its chain
  keys and frees the device reservation; :meth:`~PagedKVCache.resume`
  re-admits it under a new id through ``import_blocks``, adopting
  whatever prefix is still on device. The CRC/base64 encode can run
  OFF the drive thread through the async-ckpt double-buffer idiom
  (:class:`_OffloadWorker`); the record's ready event gates readers.

Capacity failures are a typed :class:`AdmissionError` carrying the
needed/free block counts — an admission-control signal the engine (or a
load balancer above it) can act on, categorically different from an
allocator OOM.

Threading contract: the allocator is NOT internally locked. All
bookkeeping mutation is driven by the engine's single drive thread
(``EngineFront`` serializes concurrent ``generate`` callers on its drive
lock before any of them steps the engine); an external caller sharing a
pool across threads must bring its own mutual exclusion. The
concurrency-analysis plane (``tony_tpu.analysis.concurrency``) audits
that discipline, and the threaded kvcache stress in
``tests/test_concurrency.py`` drives this class from N threads through
witnessed locks with the refcount/free/LRU partition pinned at every
quiescent point.
"""

from __future__ import annotations

import base64
import queue
import threading
import zlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from tony_tpu.serve.disagg import HandoffError


def _encode_payload(kb: bytes, vb: bytes) -> Dict[str, Any]:
    """One block's wire/host payload from its raw k/v bytes — the ONE
    encoder the handoff wire, the demoted host tier, and the parked
    records all share, so every tier speaks the identical CRC-guarded
    form and :meth:`PagedKVCache._decode_block` verifies them all."""
    return {"k": base64.b64encode(kb).decode("ascii"),
            "v": base64.b64encode(vb).decode("ascii"),
            "crc": zlib.crc32(vb, zlib.crc32(kb)) & 0xFFFFFFFF}


class _OffloadWorker:
    """Host-offload encode worker — the async-ckpt double-buffer idiom
    (:class:`tony_tpu.ckpt.snapshot.AsyncCheckpointer`): the drive
    thread's batched device fetch hands raw bytes over a queue, this
    daemon thread runs the CRC/base64 encode, and a bounded semaphore
    caps in-flight records at two (the double buffer) so parking can
    never outrun host RAM. Message-passing only: the worker writes
    into exactly the record it was handed and publishes it by setting
    the record's ready event (the release half of the happens-before
    pair — readers wait on the event first), so no pool bookkeeping is
    ever touched off the drive thread and the concurrency plane's
    single-driver discipline holds with zero blessings."""

    def __init__(self, slots: int = 2):
        self._q: "queue.Queue[Optional[tuple]]" = queue.Queue()
        self._slots = threading.BoundedSemaphore(slots)
        # Error slot (AsyncCheckpointer's idiom): a failed encode parks
        # here and re-raises on the drive thread at the next check().
        self._err: Optional[BaseException] = None
        self._err_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, name="tony-kv-offload", daemon=True)
        self._thread.start()

    def submit(self, rec: Dict[str, Any],
               raw: Sequence[Tuple[bytes, bytes]]) -> None:
        self._slots.acquire()
        self._q.put((rec, list(raw)))

    def check(self) -> None:
        """Re-raise (once) any encode failure on the caller's thread."""
        with self._err_lock:
            err, self._err = self._err, None
        if err is not None:
            raise err

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            rec, raw = item
            try:
                rec["blocks"] = [_encode_payload(kb, vb)
                                 for kb, vb in raw]
            except BaseException as e:  # noqa: BLE001 — parked in the slot
                with self._err_lock:
                    self._err = e
            finally:
                rec["ready"].set()
                self._slots.release()

    def close(self) -> None:
        self._q.put(None)
        self._thread.join(timeout=10)


class AdmissionError(RuntimeError):
    """The request cannot enter the engine NOW: the block pool cannot
    host it (or it can never fit). Retry/queue/shed upstream — this is
    back-pressure, not a crash."""

    def __init__(self, message: str, *, needed_blocks: int = 0,
                 free_blocks: int = 0, retryable: bool = True):
        super().__init__(message)
        self.needed_blocks = needed_blocks
        self.free_blocks = free_blocks
        # False: the request exceeds engine capacity outright (longer
        # than the context buffer) and will never fit, even on an idle
        # engine.
        self.retryable = retryable


class PagedKVCache:
    """Host-managed block allocator over device-resident KV block pools."""

    def __init__(self, n_layers: int, kv_dim: int, *, n_blocks: int,
                 block_size: int, dtype: Any = jnp.bfloat16,
                 host_blocks: int = 0, async_offload: bool = False):
        if n_blocks <= 0 or block_size <= 0:
            raise ValueError(f"need positive n_blocks/block_size, got "
                             f"{n_blocks}/{block_size}")
        self.n_layers = int(n_layers)
        self.kv_dim = int(kv_dim)
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        self.k = jnp.zeros((n_layers, n_blocks, block_size, kv_dim), dtype)
        self.v = jnp.zeros((n_layers, n_blocks, block_size, kv_dim), dtype)
        # LIFO free list: a just-freed block is the next handed out, so
        # the reuse invariants get exercised constantly, not just under
        # pressure.
        self._free: List[int] = list(range(n_blocks - 1, -1, -1))
        self._tables: Dict[Any, List[int]] = {}
        # Prefix tier: per-block refcount (present iff allocated),
        # content-key index (key -> block, block -> key), and the LRU
        # cached tier — blocks with refcount 0 that still hold published
        # content (most-recently-freed last; reclaimed from the front
        # only when the LIFO tier is empty).
        self._refs: Dict[int, int] = {}
        self._index: Dict[str, int] = {}
        self._key_of: Dict[int, str] = {}
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        # Lifetime counters (the engine's stats surface reads them).
        self.adopted_total = 0
        self.cow_total = 0
        self.lru_evicted_total = 0
        self.revived_total = 0
        # Disaggregated handoff (tony_tpu.serve.disagg): blocks whose
        # bytes arrived over the wire via import_blocks.
        self.imported_total = 0
        # Speculative tier (tony_tpu.serve.spec): per-sequence list of
        # blocks added by spec_reserve and not yet commit-promoted, plus
        # the write cursor — the highest position VERIFIED written (the
        # boundary below which pool bytes are trustworthy; rows above it
        # are drafts that may be rolled back).
        self._spec: Dict[Any, List[int]] = {}
        self._committed: Dict[Any, int] = {}
        # Host-offload tier (PR 16): host_blocks > 0 arms a host-RAM
        # tier of wire payloads — demoted stems keyed by chain key
        # (least-recently-demoted first: the eviction order when the
        # tier fills) and parked sequences keyed by sequence id. The
        # counters feed the engine's uniform heartbeat schema.
        self.host_blocks = int(host_blocks)
        self._host_index: "OrderedDict[str, Dict[str, Any]]" = \
            OrderedDict()
        self._parked: Dict[Any, Dict[str, Any]] = {}
        self.demoted_total = 0
        self.promoted_total = 0
        self.parked_total = 0
        self.resumed_total = 0
        self._offload = (_OffloadWorker()
                         if async_offload and self.host_blocks > 0
                         else None)

    # -- capacity ----------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        """Blocks available to a new reservation: the LIFO free tier
        plus the reclaimable LRU cached tier."""
        return len(self._free) + len(self._lru)

    def blocks_for(self, length: int) -> int:
        """Blocks covering ``length`` positions."""
        return -(-max(0, int(length)) // self.block_size)

    def _alloc_block(self) -> int:
        """One fresh block: LIFO free list first; when dry, evict the
        least-recently-freed cached block (dropping its index entry —
        ref-aware by construction: only refcount-0 blocks live in the
        cached tier). Callers check capacity first; running both tiers
        dry here is an internal error."""
        if self._free:
            b = self._free.pop()
        else:
            b, _ = self._lru.popitem(last=False)
            key = self._key_of.pop(b, None)
            if key is not None:
                self._index.pop(key, None)
                # Host tier armed: spill the evicted content to host
                # RAM instead of dropping it — the ref-aware LRU order
                # becomes the demotion policy. Reclaim is stem-only,
                # so a full host tier degrades to the plain drop,
                # never an error on the allocation path.
                if self.host_blocks > 0 and self._host_reclaim(1):
                    kb, vb = self._fetch_raw([b])[0]
                    self._host_index[key] = _encode_payload(kb, vb)
                    self._host_index.move_to_end(key)
                    self.demoted_total += 1
            self.lru_evicted_total += 1
        self._refs[b] = 1
        return b

    def _release_block(self, b: int) -> None:
        """Drop one reference; at zero the block retires to the cached
        tier when published (still addressable) or the LIFO free list
        when not."""
        self._refs[b] -= 1
        if self._refs[b] > 0:
            return
        del self._refs[b]
        if b in self._key_of:
            self._lru[b] = None
            self._lru.move_to_end(b)
        else:
            self._free.append(b)

    # -- allocation --------------------------------------------------------
    def reserve(self, seq_id: Any, length: int) -> List[int]:
        """Grow ``seq_id``'s table to cover ``length`` positions,
        allocating from the free tiers; raises :class:`AdmissionError`
        (state unchanged) when the pool can't supply the growth. The
        engine reserves a request's FULL extent (prompt + max new
        tokens) at admission, so decode can never hit pool exhaustion
        mid-flight."""
        if self._spec.get(seq_id):
            # A permanent grow would interleave with the revocable tail
            # and rollback could no longer truncate by suffix.
            raise ValueError(
                f"sequence {seq_id!r} holds an uncommitted speculative "
                f"extension — commit() or rollback() it before a "
                f"permanent reserve")
        table = self._tables.setdefault(seq_id, [])
        needed = self.blocks_for(length) - len(table)
        if needed > self.free_blocks:
            raise AdmissionError(
                f"KV pool exhausted: sequence {seq_id!r} needs {needed} "
                f"more block(s) for {length} positions, "
                f"{self.free_blocks} free of {self.n_blocks} "
                f"({len(self._lru)} cached-reclaimable)",
                needed_blocks=needed, free_blocks=self.free_blocks)
        for _ in range(max(0, needed)):
            table.append(self._alloc_block())
        return list(table)

    # -- prefix sharing ----------------------------------------------------
    def match_prefix(self, keys: Sequence[str]) -> List[int]:
        """Block ids of the longest indexed chain-key prefix of
        ``keys`` — live or cached-tier blocks alike (adoption revives
        the latter). Read-only: no refcounts move here."""
        out: List[int] = []
        for key in keys:
            b = self._index.get(key)
            if b is None:
                break
            out.append(b)
        return out

    def admit_shared(self, seq_id: Any, length: int,
                     keys: Sequence[str] = ()) -> int:
        """Fresh-admission reserve with prefix adoption, atomically:
        match ``keys`` against the block index, adopt the matched chain
        (refcount++, reviving cached-tier blocks), and allocate the
        remaining ``length``-covering blocks fresh. Returns the number
        of blocks adopted. Raises :class:`AdmissionError` with NOTHING
        changed when the fresh growth cannot be supplied — a queued
        request retries whole."""
        if self._tables.get(seq_id):
            raise ValueError(f"sequence {seq_id!r} already holds blocks "
                             f"— admit_shared is a fresh-admission path")
        matched = self.match_prefix(keys)
        needed = self.blocks_for(length) - len(matched)
        # Reviving a cached-tier block consumes reclaimable capacity
        # too: count the fresh need against what is left after revival.
        revive = sum(1 for b in matched if b in self._lru)
        if needed > self.free_blocks - revive:
            raise AdmissionError(
                f"KV pool exhausted: sequence {seq_id!r} needs {needed} "
                f"fresh block(s) beyond {len(matched)} shared for "
                f"{length} positions, {self.free_blocks - revive} "
                f"available of {self.n_blocks}",
                needed_blocks=needed,
                free_blocks=self.free_blocks - revive)
        for b in matched:
            if b in self._lru:
                del self._lru[b]
                self._refs[b] = 1
                self.revived_total += 1
            else:
                self._refs[b] += 1
            self._touch_key(b)
        self.adopted_total += len(matched)
        table = matched + [self._alloc_block()
                           for _ in range(max(0, needed))]
        self._tables[seq_id] = table
        return len(matched)

    def write_index(self, seq_id: Any, pos: int) -> int:
        """Flat scatter index of position ``pos`` FOR WRITING: when the
        covering block is shared (refcount > 1), the writer first gets a
        private copy — device rows copied, table repointed, donor block
        untouched — so a shared block is never mutated. The engine
        routes every KV scatter target through here; reads (gather
        tables) stay on :meth:`flat_index`."""
        table = self._tables[seq_id]
        bi, r = divmod(int(pos), self.block_size)
        if bi >= len(table):
            raise IndexError(
                f"position {pos} beyond sequence {seq_id!r}'s "
                f"{len(table)}-block reservation")
        b = table[bi]
        if self._refs[b] > 1:
            table[bi] = self.cow_block(seq_id, bi)
            b = table[bi]
        return b * self.block_size + r

    def cow_block(self, seq_id: Any, block_i: int) -> int:
        """Copy-on-write of table slot ``block_i``: allocate a private
        block, copy the shared block's device rows into it, drop one
        reference on the donor. Raises :class:`AdmissionError` when no
        block can be supplied (the engine's admission-time pre-COW of a
        fully-matched tail makes that unreachable in steady state)."""
        table = self._tables[seq_id]
        src = table[block_i]
        if self._refs[src] <= 1:
            return src
        if self.free_blocks < 1:
            raise AdmissionError(
                f"KV pool exhausted: sequence {seq_id!r} needs 1 block "
                f"for a copy-on-write of shared block {src}, 0 free",
                needed_blocks=1, free_blocks=0)
        dst = self._alloc_block()
        self.k = self.k.at[:, dst].set(self.k[:, src])
        self.v = self.v.at[:, dst].set(self.v[:, src])
        self._refs[src] -= 1
        table[block_i] = dst
        self.cow_total += 1
        return dst

    def _touch_key(self, block: int) -> None:
        """Move ``block``'s index entry to the recent end — the digest
        advertises the LAST ``limit`` keys, so recency must mean
        most-recently-USED: without the touch, a popular system-prompt
        stem published on day one ages out of the digest behind every
        unique conversation tail, and the router's overlap score
        collapses to zero for exactly the most-shared prefixes."""
        key = self._key_of.get(block)
        if key is not None:
            del self._index[key]
            self._index[key] = block

    def publish_block(self, seq_id: Any, block_i: int, key: str) -> bool:
        """Index table slot ``block_i`` under chain-``key`` so later
        admissions can adopt it. First publisher wins: an existing
        index entry for ``key`` (same content, another block) stays —
        repointing would strand nothing but churn the digest — but a
        re-publication refreshes its digest recency (a second producer
        of the same content proves it hot). The CALLER owns the
        correctness contract: every position of the block must be
        verified-written (full block, inside the committed extent)."""
        table = self._tables[seq_id]
        b = table[block_i]
        # A device copy supersedes a demoted host copy of the same key
        # (identical bytes by the content-address contract): dropping
        # the shadow keeps the device/host key partition disjoint —
        # the invariant the threaded stress pins at every barrier.
        self._host_index.pop(key, None)
        if key in self._index:
            self._touch_key(self._index[key])
            return False
        if b in self._key_of:
            return False
        self._index[key] = b
        self._key_of[b] = key
        return True

    def digest(self, limit: int = 256) -> List[str]:
        """Up to ``limit`` most-recently-used chain keys (publication
        and adoption both refresh recency) — the compact content
        advertisement a replica ships on its heartbeat for the
        router's overlap scoring."""
        keys = list(self._index)
        return keys[-limit:]

    def shared_blocks(self) -> int:
        """Blocks currently referenced by more than one table."""
        return sum(1 for r in self._refs.values() if r > 1)

    def ref(self, block: int) -> int:
        """Current refcount of ``block`` (0 = free/cached tier)."""
        return self._refs.get(block, 0)

    def cached_blocks(self) -> List[int]:
        """The LRU cached tier, least-recently-freed first (test
        surface for the partition + eviction-order invariants)."""
        return list(self._lru)

    # -- disaggregated handoff (tony_tpu.serve.disagg) ---------------------
    def wire_header(self) -> Dict[str, Any]:
        """The geometry a block payload must match to be importable —
        shipped with every handoff so a mis-paired fleet fails loudly
        (typed) instead of gathering garbage."""
        return {"n_layers": self.n_layers, "kv_dim": self.kv_dim,
                "block_size": self.block_size,
                "dtype": str(np.dtype(self.k.dtype))}

    def export_blocks(self, seq_id: Any, length: int) -> List[Dict[str, Any]]:
        """Wire payloads of the blocks covering ``length`` positions of
        ``seq_id`` — per block, the raw ``[n_layers, block_size,
        kv_dim]`` k and v bytes (base64 for the JSON-lines RPC) plus a
        CRC32 over the concatenated raw bytes, the ckpt plane's
        chunk-checksum idiom (:mod:`tony_tpu.ckpt.format`). Positions
        past ``length`` inside the tail block ship as-is: stale bytes
        are provably unread on the importer too (the same absolute-
        position masking contract), so the CRC guards the WIRE, not
        content identity. Read-only — no bookkeeping moves."""
        table = self._tables[seq_id]
        nb = self.blocks_for(length)
        if nb > len(table):
            raise ValueError(
                f"cannot export {length} positions for {seq_id!r}: only "
                f"{len(table)} block(s) reserved")
        return [_encode_payload(kb, vb)
                for kb, vb in self._fetch_raw(table[:nb])]

    def _fetch_raw(self, ids: Sequence[int]) -> List[Tuple[bytes, bytes]]:
        """Raw host k/v bytes of pool blocks ``ids`` — ONE batched
        device fetch per pool, not one per block (the export / demote /
        park fast path; the CRC/base64 encode can then run off the
        drive thread)."""
        idx = np.asarray(list(ids), np.int32)
        kh = np.asarray(self.k[:, idx])
        vh = np.asarray(self.v[:, idx])
        return [(np.ascontiguousarray(kh[:, i]).tobytes(),
                 np.ascontiguousarray(vh[:, i]).tobytes())
                for i in range(len(idx))]

    def _decode_block(self, blk: Dict[str, Any]) -> tuple:
        """Decode + CRC-verify one wire block payload into host
        ``[n_layers, block_size, kv_dim]`` arrays; raises
        :class:`~tony_tpu.serve.disagg.HandoffError` (non-retryable —
        a resend of the same corrupt payload cannot heal it; the
        SHIPPER owns transport retries) on any mismatch."""
        try:
            kb = base64.b64decode(blk["k"])
            vb = base64.b64decode(blk["v"])
            crc = int(blk["crc"])
        except (KeyError, TypeError, ValueError) as e:
            raise HandoffError(f"malformed block payload: {e}",
                               retryable=False) from e
        if (zlib.crc32(vb, zlib.crc32(kb)) & 0xFFFFFFFF) != crc:
            raise HandoffError(
                f"block payload CRC mismatch (got "
                f"{zlib.crc32(vb, zlib.crc32(kb)) & 0xFFFFFFFF:#010x}, "
                f"want {crc:#010x})", retryable=False)
        shape = (self.n_layers, self.block_size, self.kv_dim)
        dt = np.dtype(self.k.dtype)
        want = int(np.prod(shape)) * dt.itemsize
        if len(kb) != want or len(vb) != want:
            raise HandoffError(
                f"block payload geometry mismatch: {len(kb)}/{len(vb)} "
                f"bytes vs expected {want} for {shape} {dt}",
                retryable=False)
        return (np.frombuffer(kb, dt).reshape(shape),
                np.frombuffer(vb, dt).reshape(shape))

    def import_blocks(self, seq_id: Any, length: int,
                      blocks: Sequence[Dict[str, Any]], *,
                      keys: Sequence[str] = (), offset: int = 0) -> int:
        """Fresh-admission import of a shipped prefill: adopt the first
        ``offset`` blocks from the local prefix index via ``keys`` (the
        receiver half of the offer/import handshake — a shipped
        shared-prefix stem is adopted, never re-transferred), write the
        shipped block payloads into freshly-allocated pool blocks, and
        allocate the rest of the ``length``-covering reservation fresh.
        Returns the number of blocks adopted.

        Atomic like :meth:`admit_shared`: every raising check — payload
        CRC/geometry, the offered prefix still matching, pool capacity —
        runs BEFORE any bookkeeping or device byte moves, so an
        :class:`AdmissionError` (pool pressure, retryable upstream) or
        :class:`~tony_tpu.serve.disagg.HandoffError` leaves the cache
        state-unchanged and the shipper retries whole. Imported blocks
        are private (refcount 1) until the engine's write path touches
        them; adopted blocks keep the COW contract — an import can never
        mutate a shared block."""
        if self._tables.get(seq_id):
            raise ValueError(f"sequence {seq_id!r} already holds blocks "
                             f"— import_blocks is a fresh-admission path")
        offset = int(offset)
        nb = self.blocks_for(length)
        if offset < 0 or offset + len(blocks) > nb:
            raise HandoffError(
                f"import geometry mismatch: offset {offset} + "
                f"{len(blocks)} shipped block(s) exceed the "
                f"{nb}-block reservation for {length} positions",
                retryable=False)
        # 1. Decode + verify every payload (raises, nothing changed).
        arrs = [self._decode_block(b) for b in blocks]
        # 2. The offered prefix must still match — it can evaporate
        #    between offer and import (LRU reclaim under pressure). The
        #    CURRENT match count rides the error so the shipper re-ships
        #    exactly the missing tail.
        matched = self.match_prefix(list(keys)[:offset])
        if len(matched) < offset:
            raise HandoffError(
                f"offered prefix evaporated: {len(matched)} of {offset} "
                f"block(s) still indexed", matched=len(matched))
        # 3. Capacity, revival-aware like admit_shared.
        revive = sum(1 for b in matched if b in self._lru)
        needed = nb - offset
        if needed > self.free_blocks - revive:
            raise AdmissionError(
                f"KV pool exhausted: sequence {seq_id!r} needs {needed} "
                f"fresh block(s) beyond {offset} adopted for {length} "
                f"positions, {self.free_blocks - revive} available of "
                f"{self.n_blocks}",
                needed_blocks=needed,
                free_blocks=self.free_blocks - revive)
        # 4. Commit: adopt, then write the shipped bytes into fresh
        #    blocks, then cover the tail.
        for b in matched:
            if b in self._lru:
                del self._lru[b]
                self._refs[b] = 1
                self.revived_total += 1
            else:
                self._refs[b] += 1
            self._touch_key(b)
        self.adopted_total += len(matched)
        table = list(matched)
        if arrs:
            dsts = [self._alloc_block() for _ in arrs]
            # ONE batched scatter per pool, not one full-pool copy per
            # block — the import is on the request latency path.
            idx = jnp.asarray(dsts)
            self.k = self.k.at[:, idx].set(
                jnp.asarray(np.stack([a[0] for a in arrs], axis=1)))
            self.v = self.v.at[:, idx].set(
                jnp.asarray(np.stack([a[1] for a in arrs], axis=1)))
            table.extend(dsts)
        self.imported_total += len(arrs)
        while len(table) < nb:
            table.append(self._alloc_block())
        self._tables[seq_id] = table
        return len(matched)

    # -- host-offload tier (PR 16) -----------------------------------------
    @property
    def host_blocks_used(self) -> int:
        """Host-tier occupancy in blocks: demoted stems + every parked
        record's payloads (in-flight async encodes count — their extent
        is known at submit)."""
        return len(self._host_index) \
            + sum(r["n"] for r in self._parked.values())

    def _host_reclaim(self, need: int) -> bool:
        """Make room for ``need`` more host payloads by dropping the
        least-recently-demoted stems. Parked records are never victims
        — parking is an explicit contract with the engine, stem
        demotion opportunistic. False when the tier cannot hold
        ``need`` even with every stem dropped."""
        if need > self.host_blocks:
            return False
        while self.host_blocks_used + need > self.host_blocks \
                and self._host_index:
            self._host_index.popitem(last=False)
        return self.host_blocks_used + need <= self.host_blocks

    def demote(self, count: int = 1) -> int:
        """Demote up to ``count`` least-recently-freed cached-tier
        blocks to the host tier: ONE batched device fetch, payloads
        stashed under the blocks' chain keys, device blocks to the
        LIFO free list. The existing ref-aware LRU order IS the
        demotion policy — only refcount-0 published blocks live in the
        cached tier, and the front of the order is the coldest.
        Returns blocks demoted (0 with the tier off or nothing
        demotable)."""
        if self.host_blocks <= 0 or count <= 0:
            return 0
        victims = list(self._lru)[:count]
        if victims and not self._host_reclaim(len(victims)):
            victims = victims[:max(
                0, self.host_blocks - self.host_blocks_used)]
        if not victims:
            return 0
        raw = self._fetch_raw(victims)
        for b, (kb, vb) in zip(victims, raw):
            key = self._key_of.pop(b)
            self._index.pop(key, None)
            del self._lru[b]
            self._free.append(b)
            self._host_index[key] = _encode_payload(kb, vb)
            self._host_index.move_to_end(key)
        self.demoted_total += len(victims)
        return len(victims)

    def promote(self, keys: Sequence[str]) -> int:
        """Re-stage the host-tier run of ``keys`` that picks up where
        the device index stops matching: CRC-verify every payload
        FIRST (a corrupt host byte raises :class:`HandoffError` with
        device and host tiers unchanged), then ONE batched scatter
        into fresh blocks, indexed refcount-0 in the cached tier — the
        ``admit_shared`` that follows adopts them like any published
        stem. Degrades under pool pressure instead of raising: only
        the LIFO tier is consumed (allocating through LRU eviction
        could evict — or re-demote — the very chain being promoted)
        and the run truncates to what fits. Returns blocks promoted."""
        if self.host_blocks <= 0 or not self._host_index:
            return 0
        keys = list(keys)
        start = len(self.match_prefix(keys))
        run: List[str] = []
        for key in keys[start:]:
            if key not in self._host_index:
                break
            run.append(key)
        run = run[:len(self._free)]
        if not run:
            return 0
        arrs = [self._decode_block(self._host_index[k]) for k in run]
        dsts = [self._free.pop() for _ in run]
        idx = jnp.asarray(dsts)
        self.k = self.k.at[:, idx].set(
            jnp.asarray(np.stack([a[0] for a in arrs], axis=1)))
        self.v = self.v.at[:, idx].set(
            jnp.asarray(np.stack([a[1] for a in arrs], axis=1)))
        for key, b in zip(run, dsts):
            del self._host_index[key]
            self._index[key] = b
            self._key_of[b] = key
            self._lru[b] = None
            self._lru.move_to_end(b)
        self.promoted_total += len(run)
        return len(run)

    def discard_host(self, keys: Sequence[str]) -> int:
        """Drop host-tier stem entries for ``keys`` — the corrupt-
        payload recovery path (a failed :meth:`promote` must not leave
        the poison entry to fail every later admission; the rows
        recompute fresh). Returns entries dropped."""
        n = 0
        for key in keys:
            if self._host_index.pop(key, None) is not None:
                n += 1
        return n

    def host_keys(self) -> List[str]:
        """Demoted-stem chain keys, least-recently-demoted first (test
        surface for the host-tier partition invariants)."""
        return list(self._host_index)

    def flush_prefix(self) -> int:
        """Invalidate every published prefix entry — the hot-weight-swap
        hygiene step (tony_tpu.serve.swap): indexed blocks and demoted
        host stems hold rows computed under the OLD weights, so a
        post-swap admission adopting any of them would stream a
        mixed-version answer. Unindexes every chain key (refcount-0
        LRU residents move to the free list; a still-referenced block
        keeps its rows until its sequence releases it, but can no
        longer be adopted) and drops the whole host stem tier. Parked
        conversation records are deliberately KEPT — continuity is
        their explicit contract (engine docs). Returns entries
        invalidated (device + host)."""
        n = len(self._index) + len(self._host_index)
        for b in list(self._lru):
            del self._lru[b]
            self._free.append(b)
        for b in list(self._key_of):
            key = self._key_of.pop(b)
            self._index.pop(key, None)
        self._host_index.clear()
        return n

    def export_keys(self, keys: Sequence[str]) -> List[Dict[str, Any]]:
        """Wire payloads of the device blocks indexed under ``keys``
        (every key must be indexed — the persistent prefix store only
        persists fully-on-device chains). ONE batched fetch, read-only."""
        ids: List[int] = []
        for key in keys:
            b = self._index.get(key)
            if b is None:
                raise KeyError(f"chain key {key!r} not indexed")
            ids.append(b)
        return [_encode_payload(kb, vb)
                for kb, vb in self._fetch_raw(ids)]

    def park(self, seq_id: Any, length: int, *,
             keys: Sequence[str] = ()) -> int:
        """Park ``seq_id``: ONE batched device fetch of the blocks
        covering ``length`` positions, stashed with the full blocks'
        chain ``keys`` (the resume-time adoption probe) as a host-tier
        record, then the device reservation is freed. With the async
        :class:`_OffloadWorker` armed the CRC/base64 encode runs off
        the drive thread, double-buffered; the record's ready event
        gates any reader. Raises :class:`AdmissionError` (state
        unchanged) when the tier is off or cannot hold the record —
        the engine then falls back to a plain eviction."""
        if seq_id in self._parked:
            raise ValueError(f"sequence {seq_id!r} is already parked")
        table = self._tables.get(seq_id)
        nb = self.blocks_for(length)
        if table is None or nb > len(table):
            raise ValueError(
                f"cannot park {length} positions for {seq_id!r}: "
                f"{0 if table is None else len(table)} block(s) held")
        keys = [str(k) for k in keys]
        if len(keys) != int(length) // self.block_size:
            raise ValueError(
                f"park needs one chain key per FULL block: got "
                f"{len(keys)} for {length} positions "
                f"(block_size {self.block_size})")
        if self.host_blocks <= 0 or not self._host_reclaim(nb):
            raise AdmissionError(
                f"host tier cannot hold {nb} block(s) for parked "
                f"sequence {seq_id!r} "
                f"({self.host_blocks_used}/{self.host_blocks} used)",
                needed_blocks=nb,
                free_blocks=max(0, self.host_blocks
                                - self.host_blocks_used))
        raw = self._fetch_raw(table[:nb])
        rec: Dict[str, Any] = {"length": int(length), "keys": keys,
                               "n": nb, "ready": threading.Event(),
                               "blocks": None}
        if self._offload is not None:
            self._offload.submit(rec, raw)
        else:
            rec["blocks"] = [_encode_payload(kb, vb) for kb, vb in raw]
            rec["ready"].set()
        self._parked[seq_id] = rec
        self.free_seq(seq_id)
        self.parked_total += 1
        return nb

    def resume(self, new_id: Any, length: int, parked_id: Any) -> int:
        """Re-admit parked ``parked_id`` as ``new_id`` covering
        ``length`` total positions through :meth:`import_blocks`'
        atomic path: the chain-key prefix still on device is adopted,
        the rest re-stages from the host payloads (CRC-verified before
        any bookkeeping moves), the remainder of the reservation
        allocates fresh. The record is consumed only on success:
        :class:`HandoffError` (host corruption) and
        :class:`AdmissionError` (pool pressure) leave the pool AND the
        record unchanged, so the caller can degrade to a re-prefill —
        typed and counted, never wedged. Returns device blocks
        adopted."""
        rec = self._parked.get(parked_id)
        if rec is None:
            raise KeyError(f"no parked sequence {parked_id!r}")
        rec["ready"].wait()
        if self._offload is not None:
            self._offload.check()
        if rec["blocks"] is None:
            raise HandoffError(
                f"parked sequence {parked_id!r} lost its host payloads "
                f"(offload encode failed)", retryable=False)
        keys = rec["keys"]
        matched = len(self.match_prefix(keys))
        adopted = self.import_blocks(
            new_id, length, rec["blocks"][matched:], keys=keys,
            offset=matched)
        del self._parked[parked_id]
        self.resumed_total += 1
        return adopted

    def unpark(self, parked_id: Any) -> int:
        """Drop a parked record (conversation diverged, engine-side
        degrade to re-prefill, or a re-park of the same conversation).
        Idempotent; waits out an in-flight async encode so the record
        is never orphaned mid-write. Returns host blocks released."""
        rec = self._parked.pop(parked_id, None)
        if rec is None:
            return 0
        rec["ready"].wait()
        return rec["n"]

    def parked_ids(self) -> List[Any]:
        """Parked sequence ids (test + digest surface)."""
        return list(self._parked)

    def close(self) -> None:
        """Join the async offload worker — the thread-hygiene contract
        (whoever builds an async-armed cache owns its teardown; the
        sync default owns no thread and this is a no-op)."""
        if self._offload is not None:
            self._offload.close()
            self._offload = None

    # -- speculative tier (tony_tpu.serve.spec) ----------------------------
    def committed_len(self, seq_id: Any) -> int:
        """The write cursor: positions ``[0, committed_len)`` hold
        verified rows; anything above is a revocable draft."""
        return self._committed.get(seq_id, 0)

    def spec_reserve(self, seq_id: Any, length: int) -> List[int]:
        """Grow ``seq_id``'s table to cover ``length`` positions as a
        REVOCABLE extension: blocks added here are tracked separately so
        :meth:`rollback` can return exactly them. Raises
        :class:`AdmissionError` (state unchanged) on pool pressure. A
        table that already covers ``length`` (the engine's full-extent
        admission reservation) grows nothing — the call then only
        asserts coverage, and the later commit/rollback pair maintains
        the write cursor."""
        table = self._tables.setdefault(seq_id, [])
        needed = self.blocks_for(length) - len(table)
        if needed > self.free_blocks:
            raise AdmissionError(
                f"KV pool exhausted: sequence {seq_id!r} needs {needed} "
                f"more block(s) for a {length}-position speculative "
                f"extension, {self.free_blocks} free of {self.n_blocks}",
                needed_blocks=needed, free_blocks=self.free_blocks)
        if needed > 0:
            added = [self._alloc_block() for _ in range(needed)]
            table.extend(added)
            self._spec.setdefault(seq_id, []).extend(added)
        return list(table)

    def commit(self, seq_id: Any, length: int) -> None:
        """Advance the write cursor to ``length`` (the accepted length),
        promoting the speculative blocks that cover it to permanent.
        Never moves the cursor backwards; ``length`` must already be
        covered by the table."""
        table = self._tables.get(seq_id, [])
        need = self.blocks_for(length)
        if need > len(table):
            raise ValueError(
                f"cannot commit {length} positions for {seq_id!r}: only "
                f"{len(table)} block(s) reserved "
                f"({len(table) * self.block_size} positions)")
        spec = self._spec.get(seq_id, [])
        promote = max(0, need - (len(table) - len(spec)))
        if promote:
            self._spec[seq_id] = spec[promote:]
        self._committed[seq_id] = max(self._committed.get(seq_id, 0),
                                      int(length))

    def rollback(self, seq_id: Any) -> int:
        """Truncate ``seq_id``'s table back to its committed extent:
        every still-speculative block returns to the free list in
        reverse allocation order (so the LIFO handout order is the
        mirror of the allocation — the reuse test pins it). The write
        cursor is untouched: it already names the accepted length.
        Speculative blocks are private by construction (fresh-allocated,
        never published), so this can never strand a shared block — an
        adopted prefix below the cursor keeps every reference. Returns
        the number of blocks freed (0 when the reservation was
        full-extent and speculation grew nothing)."""
        spec = self._spec.pop(seq_id, [])
        if spec:
            table = self._tables[seq_id]
            del table[len(table) - len(spec):]
            for b in reversed(spec):
                self._release_block(b)
        return len(spec)

    def free_seq(self, seq_id: Any) -> int:
        """Drop all of ``seq_id``'s references — the speculative tail
        included; returns the table length (0 for an unknown id —
        idempotent eviction). Published blocks the sequence was the
        last holder of retire to the cached tier, still adoptable by a
        follow-up request (the recently-evicted-conversation hit)."""
        self._spec.pop(seq_id, None)
        self._committed.pop(seq_id, None)
        table = self._tables.pop(seq_id, [])
        for b in reversed(table):
            self._release_block(b)
        return len(table)

    def table(self, seq_id: Any) -> List[int]:
        return list(self._tables.get(seq_id, []))

    def owned_blocks(self) -> Dict[Any, List[int]]:
        """Live ownership snapshot (test surface for the alloc/free/reuse
        invariants: refcounts partition the pool with the free tiers;
        tables may intersect exactly on shared prefix blocks)."""
        return {sid: list(t) for sid, t in self._tables.items()}

    # -- device-side addressing --------------------------------------------
    def table_array(self, seq_ids: Sequence[Any], nb_max: int) -> np.ndarray:
        """Padded int32 ``[len(seq_ids), nb_max]`` block tables for the
        jitted step's gather (pad entries point at block 0 — gathered
        bytes there are masked by position before any row reads them)."""
        out = np.zeros((len(seq_ids), nb_max), np.int32)
        for i, sid in enumerate(seq_ids):
            t = self._tables.get(sid, [])
            if len(t) > nb_max:
                raise ValueError(
                    f"sequence {sid!r} holds {len(t)} blocks > nb_max="
                    f"{nb_max}")
            out[i, :len(t)] = t
        return out

    def flat_index(self, seq_id: Any, pos: int) -> int:
        """Flat scatter index of position ``pos`` into the
        ``[n_blocks·block_size]``-flattened pool (read addressing; a
        WRITE target must go through :meth:`write_index`)."""
        table = self._tables[seq_id]
        b, r = divmod(int(pos), self.block_size)
        if b >= len(table):
            raise IndexError(
                f"position {pos} beyond sequence {seq_id!r}'s "
                f"{len(table)}-block reservation")
        return table[b] * self.block_size + r

    @property
    def oob_index(self) -> int:
        """One-past-the-pool flat index: scatters routed here with
        ``mode='drop'`` write nothing (padding rows, dummy batch slots)."""
        return self.n_blocks * self.block_size
