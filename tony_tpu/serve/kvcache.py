"""Paged KV cache: a fixed-size block pool with per-sequence block tables.

The pool is two device arrays ``[n_layers, n_blocks, block_size, kv_dim]``
(k and v); a sequence owns an ordered list of block ids (its *block
table*) covering positions ``[0, len)`` — position ``p`` lives at row
``p % block_size`` of block ``table[p // block_size]``. Allocation is
host-side bookkeeping only (a free list of ids); the device arrays are
written by the engine's jitted step through flat scatter indices the
allocator hands out. Blocks are NOT zeroed on free/realloc: every
position is written before any query can attend it (the flash-decode
mask admits key ``j`` only for rows at position ``>= j``), so stale
bytes are provably unread — and the reuse test pins that.

Speculative decoding (tony_tpu.serve.spec) adds a second, revocable
allocation tier on top: :meth:`~PagedKVCache.spec_reserve` grows a
table to cover drafted-but-unverified positions, :meth:`commit`
advances the per-sequence *write cursor* to the accepted length
(promoting the blocks that cover it), and :meth:`rollback` truncates
the block table back to the committed extent, returning the rejected
extension to the free list in reverse order — so the LIFO reuse
contract holds for speculation too. Rollback is free on the device
side for the same stale-bytes reason: rows written at rejected
positions sit above every committed row's position and are simply
never gathered before the regenerating step overwrites them.

Capacity failures are a typed :class:`AdmissionError` carrying the
needed/free block counts — an admission-control signal the engine (or a
load balancer above it) can act on, categorically different from an
allocator OOM.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence

import jax.numpy as jnp
import numpy as np


class AdmissionError(RuntimeError):
    """The request cannot enter the engine NOW: the block pool cannot
    host it (or it can never fit). Retry/queue/shed upstream — this is
    back-pressure, not a crash."""

    def __init__(self, message: str, *, needed_blocks: int = 0,
                 free_blocks: int = 0, retryable: bool = True):
        super().__init__(message)
        self.needed_blocks = needed_blocks
        self.free_blocks = free_blocks
        # False: the request exceeds engine capacity outright (longer
        # than the context buffer) and will never fit, even on an idle
        # engine.
        self.retryable = retryable


class PagedKVCache:
    """Host-managed block allocator over device-resident KV block pools."""

    def __init__(self, n_layers: int, kv_dim: int, *, n_blocks: int,
                 block_size: int, dtype: Any = jnp.bfloat16):
        if n_blocks <= 0 or block_size <= 0:
            raise ValueError(f"need positive n_blocks/block_size, got "
                             f"{n_blocks}/{block_size}")
        self.n_layers = int(n_layers)
        self.kv_dim = int(kv_dim)
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        self.k = jnp.zeros((n_layers, n_blocks, block_size, kv_dim), dtype)
        self.v = jnp.zeros((n_layers, n_blocks, block_size, kv_dim), dtype)
        # LIFO free list: a just-freed block is the next handed out, so
        # the reuse invariants get exercised constantly, not just under
        # pressure.
        self._free: List[int] = list(range(n_blocks - 1, -1, -1))
        self._tables: Dict[Any, List[int]] = {}
        # Speculative tier (tony_tpu.serve.spec): per-sequence list of
        # blocks added by spec_reserve and not yet commit-promoted, plus
        # the write cursor — the highest position VERIFIED written (the
        # boundary below which pool bytes are trustworthy; rows above it
        # are drafts that may be rolled back).
        self._spec: Dict[Any, List[int]] = {}
        self._committed: Dict[Any, int] = {}

    # -- capacity ----------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def blocks_for(self, length: int) -> int:
        """Blocks covering ``length`` positions."""
        return -(-max(0, int(length)) // self.block_size)

    # -- allocation --------------------------------------------------------
    def reserve(self, seq_id: Any, length: int) -> List[int]:
        """Grow ``seq_id``'s table to cover ``length`` positions,
        allocating from the free list; raises :class:`AdmissionError`
        (state unchanged) when the pool can't supply the growth. The
        engine reserves a request's FULL extent (prompt + max new
        tokens) at admission, so decode can never hit pool exhaustion
        mid-flight."""
        if self._spec.get(seq_id):
            # A permanent grow would interleave with the revocable tail
            # and rollback could no longer truncate by suffix.
            raise ValueError(
                f"sequence {seq_id!r} holds an uncommitted speculative "
                f"extension — commit() or rollback() it before a "
                f"permanent reserve")
        table = self._tables.setdefault(seq_id, [])
        needed = self.blocks_for(length) - len(table)
        if needed > len(self._free):
            raise AdmissionError(
                f"KV pool exhausted: sequence {seq_id!r} needs {needed} "
                f"more block(s) for {length} positions, {len(self._free)} "
                f"free of {self.n_blocks}",
                needed_blocks=needed, free_blocks=len(self._free))
        for _ in range(max(0, needed)):
            table.append(self._free.pop())
        return list(table)

    # -- speculative tier (tony_tpu.serve.spec) ----------------------------
    def committed_len(self, seq_id: Any) -> int:
        """The write cursor: positions ``[0, committed_len)`` hold
        verified rows; anything above is a revocable draft."""
        return self._committed.get(seq_id, 0)

    def spec_reserve(self, seq_id: Any, length: int) -> List[int]:
        """Grow ``seq_id``'s table to cover ``length`` positions as a
        REVOCABLE extension: blocks added here are tracked separately so
        :meth:`rollback` can return exactly them. Raises
        :class:`AdmissionError` (state unchanged) on pool pressure. A
        table that already covers ``length`` (the engine's full-extent
        admission reservation) grows nothing — the call then only
        asserts coverage, and the later commit/rollback pair maintains
        the write cursor."""
        table = self._tables.setdefault(seq_id, [])
        needed = self.blocks_for(length) - len(table)
        if needed > len(self._free):
            raise AdmissionError(
                f"KV pool exhausted: sequence {seq_id!r} needs {needed} "
                f"more block(s) for a {length}-position speculative "
                f"extension, {len(self._free)} free of {self.n_blocks}",
                needed_blocks=needed, free_blocks=len(self._free))
        if needed > 0:
            added = [self._free.pop() for _ in range(needed)]
            table.extend(added)
            self._spec.setdefault(seq_id, []).extend(added)
        return list(table)

    def commit(self, seq_id: Any, length: int) -> None:
        """Advance the write cursor to ``length`` (the accepted length),
        promoting the speculative blocks that cover it to permanent.
        Never moves the cursor backwards; ``length`` must already be
        covered by the table."""
        table = self._tables.get(seq_id, [])
        need = self.blocks_for(length)
        if need > len(table):
            raise ValueError(
                f"cannot commit {length} positions for {seq_id!r}: only "
                f"{len(table)} block(s) reserved "
                f"({len(table) * self.block_size} positions)")
        spec = self._spec.get(seq_id, [])
        promote = max(0, need - (len(table) - len(spec)))
        if promote:
            self._spec[seq_id] = spec[promote:]
        self._committed[seq_id] = max(self._committed.get(seq_id, 0),
                                      int(length))

    def rollback(self, seq_id: Any) -> int:
        """Truncate ``seq_id``'s table back to its committed extent:
        every still-speculative block returns to the free list in
        reverse allocation order (so the LIFO handout order is the
        mirror of the allocation — the reuse test pins it). The write
        cursor is untouched: it already names the accepted length.
        Returns the number of blocks freed (0 when the reservation was
        full-extent and speculation grew nothing)."""
        spec = self._spec.pop(seq_id, [])
        if spec:
            table = self._tables[seq_id]
            del table[len(table) - len(spec):]
            self._free.extend(reversed(spec))
        return len(spec)

    def free_seq(self, seq_id: Any) -> int:
        """Return all of ``seq_id``'s blocks to the pool — the
        speculative tail included; returns the count (0 for an unknown
        id — idempotent eviction)."""
        self._spec.pop(seq_id, None)
        self._committed.pop(seq_id, None)
        table = self._tables.pop(seq_id, [])
        self._free.extend(reversed(table))
        return len(table)

    def table(self, seq_id: Any) -> List[int]:
        return list(self._tables.get(seq_id, []))

    def owned_blocks(self) -> Dict[Any, List[int]]:
        """Live ownership snapshot (test surface for the alloc/free/reuse
        invariants: disjoint tables, free+owned partitions the pool)."""
        return {sid: list(t) for sid, t in self._tables.items()}

    # -- device-side addressing --------------------------------------------
    def table_array(self, seq_ids: Sequence[Any], nb_max: int) -> np.ndarray:
        """Padded int32 ``[len(seq_ids), nb_max]`` block tables for the
        jitted step's gather (pad entries point at block 0 — gathered
        bytes there are masked by position before any row reads them)."""
        out = np.zeros((len(seq_ids), nb_max), np.int32)
        for i, sid in enumerate(seq_ids):
            t = self._tables.get(sid, [])
            if len(t) > nb_max:
                raise ValueError(
                    f"sequence {sid!r} holds {len(t)} blocks > nb_max="
                    f"{nb_max}")
            out[i, :len(t)] = t
        return out

    def flat_index(self, seq_id: Any, pos: int) -> int:
        """Flat scatter index of position ``pos`` into the
        ``[n_blocks·block_size]``-flattened pool."""
        table = self._tables[seq_id]
        b, r = divmod(int(pos), self.block_size)
        if b >= len(table):
            raise IndexError(
                f"position {pos} beyond sequence {seq_id!r}'s "
                f"{len(table)}-block reservation")
        return table[b] * self.block_size + r

    @property
    def oob_index(self) -> int:
        """One-past-the-pool flat index: scatters routed here with
        ``mode='drop'`` write nothing (padding rows, dummy batch slots)."""
        return self.n_blocks * self.block_size
