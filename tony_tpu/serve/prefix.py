"""Block-level prefix hashing: the shared content-address scheme of the
prefix cache (tony_tpu.serve.kvcache) and the cross-replica router
(tony_tpu.serve.router).

A KV row at position ``p`` depends on the ENTIRE token prefix
``tokens[0..p]`` (attention mixes every earlier position through every
layer), so a cached block is only reusable when the whole prefix up to
its last position matches — not just the block's own tokens. The block
key is therefore a CHAIN hash: ``key_i = H(key_{i-1} || tokens of block
i)``, computed over block-aligned chunks only (a partial tail block is
never addressable — its rows would be re-derived under a longer prefix
later and the key could not distinguish the two).

Deterministic across processes on purpose (blake2b over the token
bytes, not Python's randomized ``hash``): the router computes a
prompt's chain keys on the gateway and matches them against the block
digests each replica carries on its heartbeat — both sides must derive
the identical key from the identical tokens. Jax-free by the same
layering rule as ``serve.scaling``: the gateway router and the AM read
this without paying (or breaking on) a jax import.
"""

from __future__ import annotations

import hashlib
from typing import List, Sequence

# 64-bit hex keys: short enough that a few hundred ride a JSON heartbeat
# as the replica digest, long enough that a collision (which would serve
# the WRONG cached prefix) is a non-event at pool scale (~2^-64 per
# pair; a pool holds thousands of blocks, not billions).
KEY_HEX = 16
_ROOT = "tony-prefix-v1"


def chain_keys(tokens: Sequence[int], block_size: int, *,
               prior: str = "") -> List[str]:
    """Chain keys of every FULL ``block_size``-aligned block of
    ``tokens``; ``prior`` continues an existing chain (the engine
    extends a sequence's chain incrementally as generation fills
    blocks, without rehashing the history)."""
    if block_size <= 0:
        raise ValueError(f"need positive block_size, got {block_size}")
    keys: List[str] = []
    h = prior or _ROOT
    for start in range(0, len(tokens) - block_size + 1, block_size):
        blk = tokens[start:start + block_size]
        m = hashlib.blake2b(digest_size=KEY_HEX // 2)
        m.update(h.encode())
        m.update(b"|")
        m.update(",".join(str(int(t)) for t in blk).encode())
        h = m.hexdigest()
        keys.append(h)
    return keys


def match_overlap(prompt_keys: Sequence[str], digest: Sequence[str]) -> int:
    """Longest PREFIX of ``prompt_keys`` present in ``digest`` (a
    replica's advertised block-key set) — the router's cache-overlap
    score, in blocks. Prefix, not intersection: chain keys make an
    interior hit without its ancestors impossible on the replica, so a
    gap means the digest aged the ancestor out and the chain below it
    is unusable."""
    have = set(digest)
    n = 0
    for k in prompt_keys:
        if k not in have:
            break
        n += 1
    return n
