"""Replica-scaling policy: the pure half of heartbeat-driven autoscale.

Arax's framing (PAPERS 2305.01291) — jobs declare resources, the runtime
remaps them against load — lands here as a deliberately boring control
loop: serve replicas report ``qps``/``p99_ms``/``queue_depth`` over the
executor heartbeat, the AM's monitor loop feeds the latest sample per
RUNNING replica into :func:`decide`, and applies the returned delta (one
replica per decision, with a cooldown, so the loop can't flap). This
module is jax-free and side-effect-free on purpose: the decision is unit
testable without an AM, and the AM glue (``_autoscale_serve``) stays a
dumb applier.

Since the speculative decoding lane (tony_tpu.serve.spec) the heartbeat
samples also carry ``tokens_per_forward`` and ``acceptance_rate``, so
the policy sees a replica's EFFECTIVE throughput rather than raw
forward counts — a speculative replica emitting 3 tokens per launch is
not "3x busier" than its forward count suggests. The decision matrix
below is deliberately unchanged (queue depth and p99 already measure
user-visible pressure, which is what scaling should act on); the new
fields ride along for observability and for future SLO-driven policies.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

from tony_tpu.conf import (SERVE_COOLDOWN_S, SERVE_P99_HIGH_MS,
                           SERVE_QUEUE_HIGH, SERVE_QUEUE_LOW,
                           SERVE_REPLICAS_MAX, SERVE_REPLICAS_MIN,
                           SERVE_SLO_TARGET_MS, SERVE_SLO_TARGETS,
                           serve_replicas_max_key)
from tony_tpu.serve.qos import parse_tenants


def apportion_fleet_max(floors: Dict[str, int],
                        fleet_max: int) -> Dict[str, int]:
    """Per-gang autoscale ceilings from ONE fleet-wide
    ``tony.serve.replicas.max``: every gang keeps its conf-declared
    floor, and the headroom above the summed floors is split
    proportionally to floor size (largest-remainder leftovers in
    declaration order), so the per-gang ceilings can never sum past
    the operator's fleet ceiling — a split fleet's prefill and decode
    gangs must not each inflate to the whole budget."""
    if not floors:
        return {}
    total = sum(floors.values())
    head = max(0, int(fleet_max) - total)
    out = {jt: n + head * n // total for jt, n in floors.items()}
    rem = total + head - sum(out.values())
    for jt in floors:
        if rem <= 0:
            break
        out[jt] += 1
        rem -= 1
    return out


@dataclasses.dataclass(frozen=True)
class ScalingPolicy:
    """Thresholds for one serve job type. ``queue_high``/``queue_low``
    are per-replica mean queue depths; ``p99_high_ms`` (0 = disabled)
    scales up on tail latency even when queues look shallow."""
    min_replicas: int = 1
    max_replicas: int = 1
    queue_high: float = 8.0
    queue_low: float = 1.0
    p99_high_ms: float = 0.0
    cooldown_s: float = 30.0
    # SLO mode (PR 18; 0 = off, the queue-depth matrix above verbatim):
    # a non-zero p99 target switches the hot/cold verdicts to
    # p99-vs-target — the gang scales on the USER-VISIBLE promise, from
    # the same latency windows the history plane logs, so a replayed
    # event log reproduces the live decisions exactly.
    slo_target_ms: float = 0.0
    # Per-tenant SLO targets (PR 19; ``--slo_target_ms gold:200,
    # silver:800``): each named tenant's fleet-worst p99 is measured
    # against its OWN target and the gang scales on the worst
    # p99/target ratio — one tenant blowing its promise is a scale-up
    # even when the fleet aggregate looks healthy. Composes with the
    # fleet-wide ``slo_target_ms`` (both ratios compete); a dict field
    # JSON-round-trips through SCALE_DECISION records so replay stays
    # exact, and old records without the key get the empty default.
    slo_targets: Dict[str, float] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.slo_target_ms < 0:
            raise ValueError(f"slo_target_ms must be >= 0, got "
                             f"{self.slo_target_ms}")
        for name, target in self.slo_targets.items():
            if not name or not target > 0:
                raise ValueError(
                    f"slo target for tenant {name!r} must be > 0, "
                    f"got {target!r}")
        if self.min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got "
                             f"{self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas {self.max_replicas} < min_replicas "
                f"{self.min_replicas}")
        if self.queue_low > self.queue_high:
            raise ValueError(
                f"queue_low {self.queue_low} > queue_high "
                f"{self.queue_high} would oscillate")

    @classmethod
    def from_conf(cls, conf, instances: int, *,
                  job_type: Optional[str] = None,
                  fleet_floors: Optional[Dict[str, int]] = None
                  ) -> "ScalingPolicy":
        """Policy from job config; ``instances`` (the jobtype's static
        count) is the floor and the default ceiling — autoscale is OFF
        unless the conf raises ``tony.serve.replicas.max`` above it.

        For a SPLIT fleet (``fleet_floors`` holds every serve
        jobtype's static count) the global max is a fleet ceiling:
        this gang's share comes from :func:`apportion_fleet_max`
        unless ``tony.serve.replicas.max.<jobtype>`` overrides it —
        otherwise two gangs would each scale to the whole budget and
        the fleet could reach 2x the operator's ``--max_replicas``."""
        mx = conf.get_int(SERVE_REPLICAS_MAX, instances)
        if job_type is not None:
            per = conf.get_int(serve_replicas_max_key(job_type), 0)
            if per > 0:
                mx = per
            elif fleet_floors and len(fleet_floors) > 1:
                mx = apportion_fleet_max(fleet_floors, mx)[job_type]
        return cls(
            min_replicas=conf.get_int(SERVE_REPLICAS_MIN, instances),
            max_replicas=max(mx,
                             conf.get_int(SERVE_REPLICAS_MIN, instances)),
            queue_high=conf.get_float(SERVE_QUEUE_HIGH, 8.0),
            queue_low=conf.get_float(SERVE_QUEUE_LOW, 1.0),
            p99_high_ms=conf.get_float(SERVE_P99_HIGH_MS, 0.0),
            cooldown_s=conf.get_float(SERVE_COOLDOWN_S, 30.0),
            slo_target_ms=conf.get_float(SERVE_SLO_TARGET_MS, 0.0),
            slo_targets=(parse_tenants(conf.get(SERVE_SLO_TARGETS))
                         if conf.get(SERVE_SLO_TARGETS) else {}),
        )

    @property
    def enabled(self) -> bool:
        return self.max_replicas > self.min_replicas


def decide(policy: ScalingPolicy, n_running: int,
           samples: Sequence[Dict[str, float]], *, now: float,
           last_action: Optional[float] = None) -> int:
    """The scaling delta (+1 / 0 / -1) for one serve job type.

    ``samples`` is the latest heartbeat metric dict per RUNNING replica
    (``qps``/``p99_ms``/``queue_depth``; replicas that haven't reported
    yet contribute nothing). Rules, in order:

    * below the floor (replica lost / startup): grow toward
      ``min_replicas`` immediately — no cooldown, this is repair;
    * inside the cooldown window after any action: hold;
    * **queue-depth mode** (``slo_target_ms == 0`` — the historical
      matrix, verbatim): mean queue depth above ``queue_high`` — or p99
      above ``p99_high_ms`` when enabled — and below the ceiling: +1;
      mean queue depth below ``queue_low``, p99 comfortably under the
      high-water, and above the floor: −1;
    * **SLO mode** (``slo_target_ms > 0`` or per-tenant
      ``slo_targets``): every armed promise becomes a p99/target ratio
      — the gang's worst p99 against the fleet target, plus each named
      tenant's fleet-worst p99 against its own target — and the WORST
      ratio rules: above 1.0 and below the ceiling: +1; under 0.5 AND
      mean queue depth under ``queue_low`` (latency headroom alone is
      not idleness — an empty window also reads p99=0) and above the
      floor: −1. With only the fleet target armed this is the PR 18
      single-target behavior verbatim.
    """
    if n_running < policy.min_replicas:
        return policy.min_replicas - n_running
    if last_action is not None and now - last_action < policy.cooldown_s:
        return 0
    if not samples:
        return 0
    qd = sum(float(s.get("queue_depth", 0.0)) for s in samples) \
        / len(samples)
    p99 = max(float(s.get("p99_ms", 0.0)) for s in samples)
    if policy.slo_target_ms > 0 or policy.slo_targets:
        ratios = []
        if policy.slo_target_ms > 0:
            ratios.append(p99 / policy.slo_target_ms)
        for name, target in policy.slo_targets.items():
            tenant_p99 = max(
                (float(t.get("p99_ms", 0.0))
                 for s in samples
                 for t in [(s.get("tenants") or {}).get(name)]
                 if isinstance(t, dict)), default=0.0)
            ratios.append(tenant_p99 / float(target))
        worst = max(ratios)
        hot = worst > 1.0
        cold = worst < 0.5 and qd < policy.queue_low
    else:
        hot = qd > policy.queue_high or (
            policy.p99_high_ms > 0 and p99 > policy.p99_high_ms)
        cold = qd < policy.queue_low and (
            policy.p99_high_ms <= 0 or p99 < 0.5 * policy.p99_high_ms)
    if hot and n_running < policy.max_replicas:
        return 1
    if cold and n_running > policy.min_replicas:
        return -1
    return 0


def decide_warm(policy: ScalingPolicy, warm_target: int, n_active: int,
                n_warm: int) -> int:
    """Warm-standby pool delta for one serve job type: how many
    compiled-and-idle replicas to grant (+N) or retire (−N) so the pool
    sits at ``warm_target`` — capped so active + warm never exceeds the
    policy ceiling (a full fleet holds NO standbys: every grant the
    budget allows is serving traffic; as ``decide`` scales the active
    set back down, headroom reopens and the pool refills).

    Runs AFTER :func:`decide`'s verdict is applied — the active count
    it sees already includes this tick's promotion, so the pool backfill
    and the scale-up never race for the same budget slot. Pure, like
    ``decide``: the AM owns the clock and the grants."""
    want = max(0, min(int(warm_target),
                      policy.max_replicas - int(n_active)))
    return want - int(n_warm)


def replay_decisions(records: Sequence[Dict[str, Any]]
                     ) -> List[Dict[str, Any]]:
    """Replay a job's SCALE_DECISION event records through
    :func:`decide` — the load-bearing-history acceptance check: each
    record carries the COMPLETE decide() input (policy fields, active
    count, samples, clock, last action) next to the delta the live AM
    applied, so recomputing from the log must reproduce the live run
    exactly (floats round-trip bit-exact through JSON).

    ``records`` are the event payloads (``ev["payload"]`` of each
    SCALE_DECISION). Returns one verdict dict per record:
    ``{"job_type", "logged", "replayed", "match"}`` — ``tony history``
    renders the column; a mismatch means the log stopped carrying the
    decision's true inputs, which is exactly the regression this
    guards."""
    out: List[Dict[str, Any]] = []
    for rec in records:
        policy = ScalingPolicy(**rec["policy"])
        replayed = decide(policy, int(rec["n_active"]),
                          rec.get("samples") or [],
                          now=float(rec["now"]),
                          last_action=rec.get("last_action"))
        logged = int(rec["delta"])
        out.append({"job_type": rec.get("job_type", ""),
                    "logged": logged, "replayed": replayed,
                    "match": replayed == logged})
    return out
