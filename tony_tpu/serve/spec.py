"""Speculative decoding lane: draft-and-verify over the serving plane.

The PR 10 engine pays one full target-model forward per generated token;
decode is memory-bandwidth-bound, so the MXU idles while weights stream.
This module multiplies tokens-per-forward WITHOUT changing a single
output bit on the greedy path:

1. a **draft lane** proposes ``k`` tokens autoregressively — either a
   second, smaller transformer (:class:`ModelDraft`, restored by the
   replica alongside the target through the same elastic-restore path,
   optionally on the int8 ``quant=`` lanes) or the self-drafting n-gram
   fallback (:class:`NgramDraft`, the classic prompt-lookup scheme: no
   second model, no extra forwards, surprisingly effective on the
   repetitive tails greedy decoding produces);
2. the **target verifies all k+1 positions in ONE launch**: the verify
   forward is the SAME ``(b, t)``-shaped jitted step the decode loop
   runs (:func:`tony_tpu.serve.engine.build_step_fn`) with ``k+1`` real
   rows instead of 1 — the fixed ``q_block`` row-block tiling that makes
   continuous batching bit-transparent makes verification bit-transparent
   for free, and it adds ZERO new compiles;
3. **greedy accept/reject is deterministic**: draft token ``d_j`` is
   accepted iff it equals the target's argmax at the previous row; the
   first rejected row's own argmax is emitted as the bonus token. Every
   emitted token therefore equals what sequential greedy decode would
   have produced — and because each verify row's logits are bit-identical
   to the plain decode row at that position (row independence at
   tile-multiple shapes, the serve plane's core numerics contract), the
   speculative engine's token streams AND per-token logits are pinned
   BITWISE against the non-speculative engine;
4. **rollback is free**: the verify launch scatters all k+1 candidate KV
   rows into the paged pool, then the per-sequence write cursor rolls
   back to the accepted length (:meth:`PagedKVCache.commit` /
   :meth:`~PagedKVCache.rollback`). Rejected rows sit above every
   committed position, so the stale-bytes-provably-unread contract
   guarantees they are never gathered before the regenerating step
   overwrites them — no device work at all.

Expected speedup (ROOFLINE.md §9): with per-token acceptance rate α and
depth k, tokens per target launch is ``(1 - α^{k+1}) / (1 - α)`` — the
bytes-bound decode floor divides by that factor.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from tony_tpu._trace import trace_record
from tony_tpu.serve.engine import (PagedModelRunner, ServeEngine,
                                   _bucket_of, _Seq)
from tony_tpu.serve.kvcache import AdmissionError

_record = functools.partial(trace_record, "serve")


# ---------------------------------------------------------------------------
# Draft lanes
# ---------------------------------------------------------------------------

class NgramDraft:
    """Self-drafting n-gram proposer (prompt lookup): the continuation
    after the most recent earlier occurrence of the sequence's own
    longest matched suffix. Deterministic, host-side, zero forwards —
    the lane every replica can run without training a second model.
    Greedy tails love it: a generation that enters a repeating cycle is
    predicted perfectly from its own history."""

    kind = "ngram"
    forwards = 0                       # never launches anything

    def __init__(self, max_n: int = 3, min_n: int = 1):
        if not 1 <= min_n <= max_n:
            raise ValueError(f"need 1 <= min_n <= max_n, got "
                             f"{min_n}/{max_n}")
        self.max_n = int(max_n)
        self.min_n = int(min_n)
        # Per-sequence persistent index over the REAL history:
        # rid -> ([{ngram: next} per n], indexed_len). Most recent
        # occurrence wins (later writes overwrite), extended
        # incrementally as verified tokens arrive — O(max_n) per new
        # token, so a whole generation costs O(len · max_n) instead of
        # the O(len² · max_n) a per-round rescan would put on the
        # latency path the lane exists to shorten.
        self._index: Dict[Any, Any] = {}

    def _seq_index(self, s: _Seq):
        """The sequence's index, extended over tokens appended since the
        last round (drafted tokens never enter it — rejected ones would
        poison the history; accepted ones arrive here as real)."""
        hist = s.tokens
        index, done = self._index.get(s.rid) or (
            [{} for _ in range(self.max_n + 1)], 0)
        for pos in range(done, len(hist)):
            nxt = hist[pos]
            for n in range(self.min_n, min(self.max_n, pos) + 1):
                index[n][tuple(hist[pos - n:pos])] = nxt
        self._index[s.rid] = (index, len(hist))
        return index

    def propose(self, seqs: Sequence[_Seq],
                ks: Sequence[int]) -> List[List[int]]:
        out: List[List[int]] = []
        for s, k in zip(seqs, ks):
            index = self._seq_index(s)
            hist = list(s.tokens)
            # Draft-round overlay: grams created by this round's drafts
            # are newer than anything persistent (they win lookups) but
            # die with the round — they are unverified.
            overlay: List[Dict[tuple, int]] = [
                {} for _ in range(self.max_n + 1)]
            drafts: List[int] = []
            for _ in range(k):
                nxt = None
                for n in range(min(self.max_n, len(hist) - 1),
                               self.min_n - 1, -1):
                    gram = tuple(hist[-n:])
                    nxt = overlay[n].get(gram, index[n].get(gram))
                    if nxt is not None:
                        break
                if nxt is None:
                    nxt = hist[-1]     # no match: repeat-last fallback
                drafts.append(nxt)
                hist.append(nxt)
                m = len(hist) - 1
                for n in range(self.min_n, min(self.max_n, m) + 1):
                    overlay[n][tuple(hist[m - n:m])] = nxt
            out.append(drafts)
        return out

    def observe(self, seqs: Sequence[_Seq]) -> None:
        # Accepted tokens enter the persistent index lazily on the next
        # propose (the indexed_len cursor); nothing to reconcile here.
        pass

    def evict(self, seq: _Seq) -> None:
        self._index.pop(seq.rid, None)


class ModelDraft(PagedModelRunner):
    """A second (smaller) transformer as the draft lane, run over its
    OWN paged KV cache through the IDENTICAL jitted step family the
    target engine uses (the shared
    :class:`~tony_tpu.serve.engine.PagedModelRunner` plumbing — one jit
    cache shape, one mesh/donation discipline for both lanes).

    The draft cache is managed LAZILY — permanent reservation tracks the
    verified token extent, each proposal round rides a revocable
    :meth:`~PagedKVCache.spec_reserve` extension, and the post-verify
    :meth:`~PagedKVCache.commit`/:meth:`~PagedKVCache.rollback` pair
    truncates it back to the accepted length — so the speculative
    reservation machinery is load-bearing here, not just bookkeeping
    (the target engine's full-extent admission reservation means ITS
    extensions grow nothing).

    Correctness hinge: a draft token is accepted exactly when it equals
    the target's argmax, so the fed-token prefix of an accepted run
    matches the true sequence — the draft cache rows for accepted
    positions are already right and survive the rollback."""

    kind = "model"

    def __init__(self, model: Any, params: Any, *, ctx_max: int,
                 block_size: int = 16, q_block: int = 16,
                 decode_buckets: Sequence[int] = (4, 16),
                 max_running: int = 16, n_blocks: Optional[int] = None,
                 mesh: Optional[Any] = None):
        self._init_paged(model, params, ctx_max=ctx_max,
                         block_size=block_size, q_block=q_block,
                         decode_buckets=decode_buckets,
                         max_running=max_running, n_blocks=n_blocks,
                         mesh=mesh)
        self._cursor: Dict[Any, int] = {}

    # -- cache lifecycle ---------------------------------------------------
    def _sync(self, seq: _Seq) -> bool:
        """Catch the draft cache up to the verified extent: feed
        ``tokens[cursor:p0]`` (everything but the newest, not-yet-fed
        token) as one padded row block. First sight of a sequence runs
        its whole prompt; after a fully-accepted round it is one row.
        Returns False (sequence undraftable this round, retried next)
        when the draft pool cannot host the verified extent — pool
        pressure must degrade to plain decode, never escape the loop."""
        rid = seq.rid
        p0 = len(seq.tokens) - 1
        c = self._cursor.get(rid, 0)
        if c >= p0:
            return True
        try:
            # Permanent: these rows are verified.
            self.cache.reserve(rid, p0)
        except AdmissionError:
            return False
        t_real = p0 - c
        t_pad = -(-t_real // self.q_block) * self.q_block
        tokens = np.zeros((1, t_pad), np.int32)
        tokens[0, :t_real] = seq.tokens[c:p0]
        positions = (c + np.arange(t_pad, dtype=np.int32))[None].copy()
        tables = self.cache.table_array([rid], self.nb_max)
        flat = np.full((1, t_pad), self.cache.oob_index, np.int32)
        for j in range(t_real):
            flat[0, j] = self.cache.flat_index(rid, c + j)
        self._run_fn(1, t_pad, tokens, positions, tables, flat)
        self._cursor[rid] = p0
        return True

    def propose(self, seqs: Sequence[_Seq],
                ks: Sequence[int]) -> List[List[int]]:
        """``k`` batched greedy decode steps over the draft cache; each
        step feeds the previous step's argmax (step 0 feeds the target's
        newest real token). Rows past a sequence's own depth still run
        (the batch is uniform) but scatter nowhere and bind nothing.

        Draft-pool pressure degrades PER SEQUENCE, never escapes: a
        sequence whose sync or speculative extension cannot be hosted
        drafts zero tokens this round (its returned list is empty — the
        engine verifies it as a plain decode row) and retries next
        round; extensions already granted to other sequences stay
        intact for the normal commit/rollback cycle."""
        # Effective depth per sequence: 0 when the draft cache cannot
        # host it this round (sync or extension failure).
        ks = [k if self._sync(s) else 0 for s, k in zip(seqs, ks)]
        for i, (s, k) in enumerate(zip(seqs, ks)):
            if k:
                try:
                    # Revocable coverage for the k fed rows at
                    # p0 .. p0+k-1 (atomic: state unchanged on failure).
                    self.cache.spec_reserve(s.rid,
                                            len(s.tokens) - 1 + k)
                except AdmissionError:
                    ks[i] = 0
        n = len(seqs)
        b = _bucket_of(self.decode_buckets, n)
        t = self.q_block
        kmax = max(ks) if ks else 0
        drafts: List[List[int]] = [[] for _ in seqs]
        cur = [s.tokens[-1] for s in seqs]
        # Tables are fixed for the whole round once the reservations are
        # in — build the padded array once, not once per draft step.
        tables = np.zeros((b, self.nb_max), np.int32)
        tables[:n] = self.cache.table_array(
            [s.rid for s in seqs], self.nb_max)
        for j in range(kmax):
            tokens = np.zeros((b, t), np.int32)
            positions = np.zeros((b, t), np.int32)
            flat = np.full((b, t), self.cache.oob_index, np.int32)
            for i, s in enumerate(seqs):
                pj = len(s.tokens) - 1 + j
                tokens[i, 0] = cur[i]
                positions[i] = pj + np.arange(t, dtype=np.int32)
                if j < ks[i]:
                    flat[i, 0] = self.cache.flat_index(s.rid, pj)
            logits = self._run_fn(b, t, tokens, positions, tables, flat)
            rows = np.asarray(logits[:n, 0], np.float32)
            for i in range(n):
                if j < ks[i]:
                    nxt = int(np.argmax(rows[i]))
                    drafts[i].append(nxt)
                    cur[i] = nxt
        for s, k in zip(seqs, ks):
            if k:
                self._cursor[s.rid] = len(s.tokens) - 1 + k
        return drafts

    def observe(self, seqs: Sequence[_Seq]) -> None:
        """Post-verify reconciliation: the engine has appended the
        accepted prefix + bonus to each sequence; roll the draft cache's
        cursor back to the longest fed prefix that is still true (the
        accepted rows — rejected rows' blocks return to the pool)."""
        for s in seqs:
            rid = s.rid
            c = min(self._cursor.get(rid, 0), len(s.tokens) - 1)
            self.cache.commit(rid, c)
            self.cache.rollback(rid)
            self._cursor[rid] = c

    def evict(self, seq: _Seq) -> None:
        self.cache.free_seq(seq.rid)
        self._cursor.pop(seq.rid, None)


# ---------------------------------------------------------------------------
# The speculative engine
# ---------------------------------------------------------------------------

class SpecEngine(ServeEngine):
    """Draft-and-verify continuous batching: identical admission, join,
    and evict semantics to :class:`~tony_tpu.serve.engine.ServeEngine`,
    but each iteration advances every running sequence by a VARIABLE
    number of tokens — the accepted draft prefix plus the target's bonus
    token — for exactly one target forward.

    ``draft`` is a lane object (:class:`NgramDraft` default,
    :class:`ModelDraft` via ``draft_model=``/``draft_params=``) and
    ``spec_k`` the draft depth (``<= q_block - 1``: the verify rows must
    fit the engine's fixed row block). Greedy-path outputs are pinned
    BITWISE against the plain engine — tests/test_spec.py holds token
    streams AND per-token logits across overlapping, ragged,
    block-boundary-crossing request mixes."""

    def __init__(self, model: Any, params: Any, *, spec_k: int = 4,
                 draft: Optional[Any] = None,
                 draft_model: Optional[Any] = None,
                 draft_params: Optional[Any] = None,
                 ngram_max: int = 3, **kw):
        super().__init__(model, params, **kw)
        if not 1 <= int(spec_k) <= self.q_block - 1:
            raise ValueError(
                f"spec_k must be in [1, q_block-1={self.q_block - 1}] "
                f"(the k+1 verify rows ride one row block), got {spec_k}")
        self.spec_k = int(spec_k)
        if draft is None:
            if draft_model is not None:
                draft = ModelDraft(
                    draft_model, draft_params, ctx_max=self.ctx_pad,
                    block_size=self.block_size, q_block=self.q_block,
                    decode_buckets=self.decode_buckets,
                    max_running=self.max_running, mesh=self.mesh)
            else:
                draft = NgramDraft(max_n=ngram_max)
        elif draft_model is not None:
            raise ValueError("pass draft= OR draft_model=, not both")
        self.draft = draft
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.verify_launches = 0
        self.spec_rounds = 0           # (sequence, verify-launch) pairs
        self.spec_tokens_out = 0
        _record(f"{self.tag}_spec", k=self.spec_k, draft=self.draft.kind,
                q_block=self.q_block,
                decode_buckets=list(self.decode_buckets))

    # -- the one-launch verification ---------------------------------------
    def _verify_round(self) -> None:
        seqs = list(self._running)
        ks = [min(self.spec_k, s.remaining) for s in seqs]
        drafts = self.draft.propose(seqs, ks)
        # The lane may degrade a sequence's depth (draft-pool pressure →
        # empty proposal = plain decode for that row this round); the
        # verify geometry follows what was actually drafted.
        ks = [min(k, len(d)) for k, d in zip(ks, drafts)]
        b = _bucket_of(self.decode_buckets, len(seqs))
        t = self.q_block
        tokens = np.zeros((b, t), np.int32)
        positions = np.zeros((b, t), np.int32)
        tables = np.zeros((b, self.nb_max), np.int32)
        flat = np.full((b, t), self.cache.oob_index, np.int32)
        for i, s in enumerate(seqs):
            p0 = len(s.tokens) - 1
            # Revocable coverage for the k+1 candidate rows at
            # p0 .. p0+k. Full-extent admission already covers them, so
            # this grows nothing on the target pool — but it keeps the
            # reserve→commit→rollback cursor contract uniform with the
            # draft cache (and with any future lazily-reserving engine).
            self.cache.spec_reserve(s.rid, p0 + 1 + ks[i])
            tokens[i, 0] = s.tokens[-1]
            tokens[i, 1:1 + ks[i]] = drafts[i]
            positions[i] = p0 + np.arange(t, dtype=np.int32)
            for j in range(ks[i] + 1):
                # write_index: a forked sequence's first verify rows can
                # land in an adopted prefix block (full-cover admission)
                # — COW keeps the donor's bytes untouched.
                flat[i, j] = self.cache.write_index(s.rid, p0 + j)
        tables[:len(seqs)] = self.cache.table_array(
            [s.rid for s in seqs], self.nb_max)
        logits = self._run_fn(b, t, tokens, positions, tables, flat)
        self.verify_launches += 1
        for i, s in enumerate(seqs):
            p0 = len(s.tokens) - 1
            k = ks[i]
            a = 0
            while a < k:
                row = np.asarray(logits[i, a], np.float32)
                if int(np.argmax(row)) != drafts[i][a]:
                    break
                self._emit_token(s, row)     # == the accepted draft token
                a += 1
            if s.remaining > 0:
                # The first non-accepted row's own argmax: the token
                # sequential greedy decode would have produced here.
                self._emit_token(s, np.asarray(logits[i, a], np.float32))
            self.spec_proposed += k
            self.spec_accepted += a
            self.spec_rounds += 1
            self.spec_tokens_out += len(s.tokens) - 1 - p0
            # Verified rows now cover positions [0, p0+a+1); the cursor
            # rolls back to exactly there — rejected rows above it are
            # stale bytes the next launch overwrites before any read.
            self.cache.commit(s.rid, p0 + a + 1)
            self.cache.rollback(s.rid)
        self.draft.observe(seqs)

    def step(self):
        """One engine iteration: join what fits, advance one prefill
        chunk (chunked mode), draft + verify one launch for the whole
        running batch, evict what finished."""
        results = []
        self._join(results)
        self._advance_prefill(results)
        if self._running:
            self._verify_round()
            still = []
            for s in self._running:
                if s.remaining <= 0:
                    self.draft.evict(s)
                    self._evict(s, results)
                else:
                    still.append(s)
            self._running = still
        self._steps += 1
        return results

    # -- telemetry ---------------------------------------------------------
    def _extra_stats(self) -> Dict[str, float]:
        return {
            "acceptance_rate": (self.spec_accepted / self.spec_proposed
                                if self.spec_proposed else 0.0),
            "spec_proposed": float(self.spec_proposed),
            "spec_accepted": float(self.spec_accepted),
            "verify_launches": float(self.verify_launches),
            "draft_forwards": float(getattr(self.draft, "forwards", 0)),
            # Decode tokens per verify launch (batching folded in), and
            # the per-SEQUENCE version = 1 + mean accepted run — the >1
            # multiplier speculation itself earns, batching excluded
            # (prefill-emitted tokens excluded from both, unlike the
            # global tokens_per_forward).
            "tokens_per_verify": (self.spec_tokens_out
                                  / self.verify_launches
                                  if self.verify_launches else 0.0),
            "tokens_per_seq_round": (self.spec_tokens_out
                                     / self.spec_rounds
                                     if self.spec_rounds else 0.0),
        }

    # -- static-analysis hook ---------------------------------------------
    def verify_traced(self, batch: Optional[int] = None):
        """``(jitted, example_args)`` of the canonical verify bucket for
        ``tony analyze --config spec``. The verify step IS the decode
        step family — k+1 real rows ride the same ``(b, q_block)``
        launch — so this traces the identical program the loop runs,
        and the zero-collectives + KV-pool-donation audit covers the
        speculative lane with the same pin mechanics as decode."""
        return self.decode_traced(batch)
