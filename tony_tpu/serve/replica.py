"""One serving replica: elastic-restored params + engine + RPC front.

A replica is the serve job type's user process (``python -m
tony_tpu.serve.replica``, launched by the executor like any other
workload). Startup:

1. build the registered model (``tony.serve.model`` + JSON kwargs —
   including ``quant=`` lanes, which serve through the same projections
   training used);
2. restore ONLY the params subtree of the training checkpoint through
   elastic restore onto the replica's own mesh
   (:func:`tony_tpu.ckpt.find_path_prefix` locates the subtree whatever
   the save's wrapping; ``dtype_policy="bf16"`` casts the f32 master to
   the serving dtype during shard assembly — optimizer slots are never
   even read);
3. run a :class:`~tony_tpu.serve.engine.ServeEngine` behind the
   control-plane RPC wire (same JSON-lines protocol as the AM — and the
   existing :class:`tony_tpu.proxy.ProxyServer` fronts it for gateway
   access, exactly like notebooks);
4. publish the engine's qps/p99/queue-depth to the ``TONY_SERVE_STATS``
   file the executor's heartbeat piggybacks to the AM — the signal the
   replica autoscaler acts on.

Concurrent ``generate`` RPCs drive ONE shared engine: each call submits
its request and then takes turns advancing the loop until its own
completion lands, so overlapping calls naturally join the continuous
batch.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from tony_tpu.conf import (CKPT_DIR, SERVE_AOT_CACHE, SERVE_BLOCK_SIZE,
                           SERVE_CKPT_DIR, SERVE_CTX_MAX,
                           SERVE_DEMOTE_BATCH, SERVE_DEMOTE_WATERMARK,
                           SERVE_DRAFT_CKPT_DIR, SERVE_DRAFT_MODEL,
                           SERVE_DRAFT_MODEL_KWARGS,
                           SERVE_DRAFT_NGRAM_MAX, SERVE_DTYPE_POLICY,
                           SERVE_HOST_BLOCKS, SERVE_MAX_RUNNING,
                           SERVE_MESH, SERVE_MODEL, SERVE_MODEL_KWARGS,
                           SERVE_PORT, SERVE_PREFILL_CHUNK,
                           SERVE_PREFIX_CACHE, SERVE_PREFIX_STORE,
                           SERVE_SPEC_K, SERVE_WARM_STANDBY,
                           serve_role_key, serve_warm_standby_key)
from tony_tpu.serve.engine import Completion, EngineFront, ServeEngine


class Replica:
    """Build (restore + engine) and front one serving replica."""

    def __init__(self, *, model_name: str,
                 model_kwargs: Optional[Dict[str, Any]] = None,
                 ckpt_dir: str, dtype_policy: Optional[str] = "bf16",
                 mesh: Optional[Any] = None, ctx_max: int = 2048,
                 block_size: int = 16, q_block: int = 16,
                 n_blocks: Optional[int] = None, max_running: int = 16,
                 keep_logits: bool = False, tag: str = "serve",
                 spec_k: int = 0,
                 draft_model_name: Optional[str] = None,
                 draft_model_kwargs: Optional[Dict[str, Any]] = None,
                 draft_ckpt_dir: Optional[str] = None,
                 ngram_max: int = 3,
                 prefix_cache: bool = False,
                 prefill_chunk: Optional[int] = None,
                 role: str = "colocated", host_blocks: int = 0,
                 prefix_store: Optional[str] = None,
                 aot_cache: Optional[str] = None,
                 warm_standby: bool = False,
                 demote_watermark: float = 0.0,
                 demote_batch: int = 0,
                 qos: Optional[Any] = None):
        from tony_tpu._trace import trace_record
        from tony_tpu.models import get_model
        from tony_tpu.serve.disagg import DecodeFront, PrefillFront

        # Cold-start plane (tony_tpu.ckpt.aot): a cache DIR in the conf
        # becomes a live AOTCache shared by every step family the
        # engine compiles. Built before the engine so the very first
        # bucket resolution can hit.
        self._aot = None
        if aot_cache:
            from tony_tpu.ckpt import AOTCache

            self._aot = AOTCache(aot_cache)
        self.model = get_model(model_name, **(model_kwargs or {}))
        self.mesh = mesh
        # Continuous publication (tony_tpu.publish): a published pointer
        # outranks "latest committed" — the pointer is the train gang's
        # statement of which step the fleet should serve, and a replica
        # that came up mid-stream must match the fleet it joins.
        self.ckpt_dir = ckpt_dir
        self.dtype_policy = dtype_policy
        self.q_block = q_block
        self.ctx_max = ctx_max
        from tony_tpu.publish import latest_publication

        pub = latest_publication(ckpt_dir)
        params, step, prefix = self._restore_params(
            self.model, ckpt_dir, dtype_policy=dtype_policy, mesh=mesh,
            q_block=q_block, step=pub["step"] if pub else None)
        self.restored_step = step
        if spec_k:
            # Speculative lane (tony_tpu.serve.spec): draft-and-verify.
            # A named draft model restores through the SAME elastic path
            # as the target (its own ckpt dir, or the target's when the
            # two share a save); no draft model = self-drafting n-gram.
            from tony_tpu.serve.spec import SpecEngine

            draft_kw: Dict[str, Any] = {"ngram_max": ngram_max}
            if draft_model_name:
                draft_model = get_model(draft_model_name,
                                        **(draft_model_kwargs or {}))
                draft_params, draft_step, _ = self._restore_params(
                    draft_model, draft_ckpt_dir or ckpt_dir,
                    dtype_policy=dtype_policy, mesh=mesh, q_block=q_block)
                draft_kw.update(draft_model=draft_model,
                                draft_params=draft_params)
                self.draft_restored_step = draft_step
            self.engine = SpecEngine(
                self.model, params, spec_k=spec_k, ctx_max=ctx_max,
                block_size=block_size, q_block=q_block, n_blocks=n_blocks,
                max_running=max_running, mesh=mesh,
                keep_logits=keep_logits, tag=tag,
                prefix_cache=prefix_cache, prefill_chunk=prefill_chunk,
                role=role, host_blocks=host_blocks,
                async_offload=host_blocks > 0, aot_cache=self._aot,
                warm_standby=warm_standby,
                demote_watermark=demote_watermark,
                demote_batch=demote_batch, qos=qos, **draft_kw)
        else:
            self.engine = ServeEngine(
                self.model, params, ctx_max=ctx_max,
                block_size=block_size, q_block=q_block, n_blocks=n_blocks,
                max_running=max_running, mesh=mesh,
                keep_logits=keep_logits, tag=tag,
                prefix_cache=prefix_cache, prefill_chunk=prefill_chunk,
                role=role, host_blocks=host_blocks,
                async_offload=host_blocks > 0, aot_cache=self._aot,
                warm_standby=warm_standby,
                demote_watermark=demote_watermark,
                demote_batch=demote_batch, qos=qos)
        # Seed the serving version: a replica restored from a published
        # step advertises that version on its very first heartbeat, so
        # the AM's rolling swap never re-swaps a replica that already
        # came up on the target.
        self.engine.weight_step = int(step)
        if pub is not None and pub["step"] == step:
            self.engine.weight_version = pub["version"]
        trace_record("serve", "replica", model=model_name,
                     ckpt_step=step, path_prefix=prefix,
                     dtype_policy=dtype_policy, spec_k=int(spec_k),
                     draft_model=draft_model_name or
                     ("ngram" if spec_k else None),
                     prefix_cache=bool(prefix_cache),
                     prefill_chunk=prefill_chunk, role=role,
                     mesh_axes=dict(getattr(mesh, "shape", {}) or {}))
        self.role = role
        self._front = EngineFront(self.engine)
        # Disaggregated handoff halves (tony_tpu.serve.disagg). Every
        # replica carries BOTH: the router's role-aware dispatch decides
        # which verbs see traffic, and a colocated replica answering a
        # stray kv_offer is harmless — capability is not policy.
        self._prefill_front = PrefillFront(self._front)
        self._decode_front = DecodeFront(self._front)
        # Persistent prefix store (tony_tpu.serve.kvstore): adopt the
        # persisted hot stems NOW — before the first request — so a
        # fresh replica (or a scale-up grant naming the store) serves
        # its first shared-stem prompt from disk-warmed KV instead of
        # recompute; the stats publisher exports newly-hot stems back.
        self._store = None
        if prefix_store:
            from tony_tpu.serve.kvstore import PrefixStore

            self._store = PrefixStore(prefix_store)
            self._load_stems()
        # Pre-resolve the step family when the cold-start plane is on:
        # a warm STANDBY must hold executables before promotion (that
        # is the whole point of the pool), and a cache-armed active
        # replica resolves now so its first request pays deserialize
        # milliseconds — and its misses populate the cache for every
        # later grant of the family.
        if self._aot is not None or warm_standby:
            n = self.engine.warm()
            print(f"[tony-serve-replica] warmed {n} step program(s) "
                  f"(aot hits {self.engine.aot_hits}, "
                  f"misses {self.engine.aot_misses})", flush=True)
        self._publish: Optional[Any] = None
        self.port: Optional[int] = None

    def _load_stems(self) -> None:
        """Warm the engine's prefix tier from the store — best-effort:
        a corrupt or geometry-skewed stem is skipped (that prefix
        recomputes), never a startup failure."""
        header = self.engine.cache.wire_header()
        adopted = 0
        for tip in self._store.stems():
            rec = self._store.get(tip)
            if rec is None or rec.get("header") != header:
                continue
            adopted += self.engine.adopt_stem(rec["keys"], rec["blocks"])
        if adopted:
            print(f"[tony-serve-replica] adopted {adopted} KV block(s) "
                  f"from the prefix store", flush=True)

    @staticmethod
    def _restore_params(model: Any, ckpt_dir: str, *,
                        dtype_policy: Optional[str], mesh: Optional[Any],
                        q_block: int, step: Optional[int] = None):
        """Elastic params-only restore onto the replica's mesh — shared
        by the target and the speculative lane's draft model (both are
        trained checkpoints; neither may initialize fresh weights).
        ``step`` pins a specific committed step — the hot-swap path and
        the published-pointer startup both restore a NAMED manifest,
        never whatever happens to be latest when the restore runs."""
        import flax.linen as nn
        import jax
        import jax.numpy as jnp

        from tony_tpu import ckpt
        from tony_tpu.compat import mesh_context

        sample = jnp.zeros((1, q_block), jnp.int32)

        def init():
            return nn.unbox(model.init(jax.random.PRNGKey(0),
                                       sample))["params"]

        # Template init: structure/shapes only — every value is replaced
        # by the restore below (and the restore is what the e2e test
        # pins, so a template that accidentally survived would fail it).
        if mesh is not None:
            with mesh_context(mesh):
                template = jax.jit(init)()
        else:
            template = init()
        if step is None:
            step = ckpt.latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(
                f"no committed checkpoint under {ckpt_dir} — a replica "
                f"serves a trained model, it does not initialize one")
        prefix = ckpt.find_path_prefix(ckpt_dir, template, step=step)
        params = ckpt.restore_pytree(
            ckpt_dir, template, step=step, mesh=mesh,
            dtype_policy=dtype_policy, path_prefix=prefix)
        return params, step, prefix

    # -- request path ------------------------------------------------------
    def generate(self, tokens: Sequence[int], max_new_tokens: int,
                 rid: Optional[Any] = None,
                 conv: Optional[Any] = None,
                 tenant: Optional[str] = None) -> Completion:
        """Submit one request and drive the shared engine until it
        completes. Thread-safe: concurrent callers interleave on the
        drive lock (:class:`~tony_tpu.serve.engine.EngineFront` — the
        same loop the router's in-process transport runs), so their
        requests ride one continuous batch. ``conv`` is the
        conversation handle arming park/resume on a host-tier engine;
        ``tenant`` is the QoS class the engine's admission budgets
        meter (tony_tpu.serve.qos — ignored on an unloaded engine)."""
        return self._front.generate(tokens, max_new_tokens, rid=rid,
                                    conv=conv, tenant=tenant)

    # -- disaggregated handoff (tony_tpu.serve.disagg) ---------------------
    def prefill_handoff(self, tokens: Sequence[int], max_new_tokens: int,
                        rid: Optional[Any] = None,
                        decode: Any = None,
                        conv: Optional[Any] = None,
                        tenant: Optional[str] = None) -> Completion:
        """Prefill-role request path: prefill ``tokens``, ship the KV
        blocks to ``decode`` (an address or an in-process receiver),
        return the completion the decode side drove to the end."""
        return self._prefill_front.prefill_handoff(
            tokens, max_new_tokens, rid=rid, decode=decode, conv=conv,
            tenant=tenant)

    def kv_offer(self, keys: Sequence[str]) -> int:
        return self._decode_front.kv_offer(keys)

    def kv_import(self, payload: Dict[str, Any]) -> Completion:
        return self._decode_front.kv_import(payload)

    # -- warm-standby promotion (tony_tpu.serve.scaling) -------------------
    def promote(self) -> bool:
        """AM scale-up path: leave warm standby and republish stats
        IMMEDIATELY — the session routes on warm_standby=0, and waiting
        a publish tick to become routable would hand back the very
        cold-start latency the pool exists to hide."""
        was = self.engine.promote()
        if was and self._publish is not None:
            self._publish()
        return was

    # -- hot weight swap (tony_tpu.serve.swap) -----------------------------
    def hot_swap(self, *, version: Optional[int] = None,
                 step: Optional[int] = None) -> Dict[str, Any]:
        """Swap this replica onto a published manifest IN PLACE —
        no container restart, no dropped request, no recompile.

        Three phases, and only the last needs the drive lock:

        1. resolve the target (the published pointer, or an explicit
           ``step`` pin) — pure pointer reads;
        2. restore the params subtree through the SAME elastic/dtype-
           policy path startup used, onto the live mesh, while the
           engine KEEPS SERVING the old weights (the disk + device_put
           minutes cost zero downtime);
        3. quiesce to an iteration boundary under the front's drive
           lock and flip (``EngineFront.quiesce_and_swap`` →
           ``ServeEngine.swap_params``): in-flight sequences finished
           under the old weights, the queued backlog admits under the
           new, the prefix/host tiers flushed, parked conversations
           kept.

        Any failure raises :class:`SwapError` with the old weights
        still serving (atomic-or-rolled-back); success republishes
        stats immediately so the router's down-mark lifts on the next
        heartbeat, not the next publish tick. The speculative lane's
        draft model is NOT swapped — it is a different checkpoint
        lineage; republish it by rolling the replica."""
        from tony_tpu import chaos
        from tony_tpu.serve.swap import SwapError, resolve_target

        t0 = time.monotonic()
        to_version, to_step = resolve_target(self.ckpt_dir,
                                             version=version, step=step)
        from_version = self.engine.weight_version
        chaos.crash_point("swap_before_restore")
        try:
            params, rstep, _ = self._restore_params(
                self.model, self.ckpt_dir, dtype_policy=self.dtype_policy,
                mesh=self.mesh, q_block=self.q_block, step=to_step)
        except SwapError:
            raise
        except Exception as exc:   # noqa: BLE001 — typed rollback contract
            raise SwapError(f"restore of step {to_step} failed: "
                            f"{type(exc).__name__}: {exc}") from exc
        chaos.crash_point("swap_after_restore")

        def flip() -> None:
            chaos.crash_point("swap_before_flip")
            self.engine.swap_params(params, version=to_version,
                                    step=to_step)
            chaos.crash_point("swap_after_flip")

        self._front.quiesce_and_swap(flip)
        self.restored_step = rstep
        if self._publish is not None:
            self._publish()
        return {"ok": True, "from_version": from_version,
                "to_version": to_version, "step": to_step,
                "wall_s": time.monotonic() - t0}

    def tune_warm_pads(self, history_dir: str, *,
                       limit: int = 4) -> List[int]:
        """warm() pad self-tuning (tony_tpu.serve.swap): read the
        prompt-length histograms earlier serve windows logged under
        ``history_dir`` and precompile the prefill pads the traffic
        actually used — the data-driven replacement for a caller-named
        ``prefill_pads=`` guess. Best-effort: an unreadable log warms
        nothing extra, never fails startup."""
        from tony_tpu import events as ev
        from tony_tpu.serve.swap import derive_prefill_pads

        records: List[Dict[str, Any]] = []
        try:
            for job in ev.list_jobs(history_dir):
                try:
                    records += [r for r in ev.read_events(job["path"])
                                if r.get("type") == ev.SERVE_WINDOW]
                except (OSError, ValueError):
                    continue
        except OSError:
            return []
        pads = derive_prefill_pads(
            records, q_block=self.engine.q_block,
            ctx_max=self.ctx_max, limit=limit)
        if pads:
            n = self.engine.warm(prefill_pads=pads)
            print(f"[tony-serve-replica] self-tuned prefill pads "
                  f"{pads} from the serve history ({n} program(s) "
                  f"resolved)", flush=True)
        return pads

    # -- RPC front ---------------------------------------------------------
    def rpc_handler(self) -> "_ReplicaRpcHandler":
        return _ReplicaRpcHandler(self)

    def serve_forever(self, *, host: str = "0.0.0.0", port: int = 0,
                      stats_path: Optional[str] = None,
                      stats_every_s: float = 2.0,
                      stop: Optional[threading.Event] = None) -> None:
        """Run the RPC server and the stats publisher until ``stop``."""
        from tony_tpu.rpc import RpcServer

        server = RpcServer(self.rpc_handler(), host=host, port=port)
        server.start()
        self.port = server.port
        print(f"[tony-serve-replica] listening on {server.address} "
              f"(ckpt step {self.restored_step})", flush=True)
        stop = stop or threading.Event()

        def publish() -> None:
            if not stats_path:
                return
            try:
                # rpc_port rides the stats file → heartbeat →
                # session so the request router can DIAL this
                # replica (task.port is the rendezvous port,
                # not the serve RPC) — and the prefix digest
                # rides the same payload for overlap scoring.
                self.engine.write_stats(
                    stats_path, extra={"rpc_port": server.port})
            except OSError:
                pass
            if self._store is not None:
                # Persist newly-hot stems on the publish cadence —
                # under the drive lock (the export reads the pool,
                # and the pool is only safe under one driver).
                try:
                    with self._front._drive:
                        self.engine.export_stems(self._store)
                except OSError:
                    pass

        # The promote RPC republishes through this hook so a promotion
        # is routable on the next heartbeat, not the next publish tick.
        self._publish = publish
        try:
            # First publish BEFORE the first interval: the router can
            # only dial a replica whose rpc_port reached the AM, and a
            # freshly-granted scale-up that waits a full publish tick
            # to become routable pays that tick as cold-start latency.
            publish()
            while not stop.wait(stats_every_s):
                publish()
        finally:
            # Deterministic teardown (the concurrency plane's shutdown-
            # hygiene contract): server.stop() joins the accept thread,
            # and cache.close() joins the host-offload encode worker,
            # so by the time serve_forever returns no replica thread is
            # left running.
            server.stop()
            self.engine.cache.close()


class _ReplicaRpcHandler:
    """RPC verbs of one replica (JSON-lines wire, same as the AM's)."""

    def __init__(self, replica: Replica):
        self.replica = replica

    @staticmethod
    def _wire(c: Completion) -> Dict[str, Any]:
        return c.wire()

    def rpc_generate(self, tokens: List[int], max_new_tokens: int = 16,
                     rid: Optional[str] = None,
                     conv: Optional[str] = None,
                     tenant: Optional[str] = None) -> Dict[str, Any]:
        return self._wire(self.replica.generate(tokens, max_new_tokens,
                                                rid=rid, conv=conv,
                                                tenant=tenant))

    def rpc_serve_stats(self) -> Dict[str, float]:
        return self.replica.engine.stats()

    # -- disaggregated handoff verbs (tony_tpu.serve.disagg) ---------------
    def rpc_prefill_handoff(self, tokens: List[int],
                            max_new_tokens: int = 16,
                            rid: Optional[str] = None,
                            decode_address: Optional[str] = None,
                            conv: Optional[str] = None,
                            tenant: Optional[str] = None
                            ) -> Dict[str, Any]:
        """The router's disaggregated dispatch verb: prefill here, ship
        the KV replica-to-replica to ``decode_address``, return the
        decode side's completion. Typed failures transport as
        ``"HandoffError: ..."`` on the JSON-lines wire — the router
        re-types them for its fallback split."""
        out = self.replica.prefill_handoff(tokens, max_new_tokens,
                                           rid=rid, decode=decode_address,
                                           conv=conv, tenant=tenant)
        return out if isinstance(out, dict) else self._wire(out)

    def rpc_kv_offer(self, keys: List[str]) -> int:
        return self.replica.kv_offer(keys)

    def rpc_kv_import(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        return self._wire(self.replica.kv_import(payload))

    def rpc_promote(self) -> bool:
        """The AM's scale-up verb against a warm standby (idempotent —
        a retried promotion of an already-active replica returns
        False and changes nothing)."""
        return self.replica.promote()

    def rpc_swap(self, version: Optional[int] = None,
                 step: Optional[int] = None) -> Dict[str, Any]:
        """The AM's rolling-fleet verb: hot-swap this replica onto the
        published manifest (or an explicit ``step`` pin). A failure
        transports as ``"SwapError: ..."`` on the JSON-lines wire —
        the replica is still serving its OLD weights when the AM
        reads it (atomic-or-rolled-back)."""
        return self.replica.hot_swap(version=version, step=step)


def main() -> int:
    """``python -m tony_tpu.serve.replica`` — the serve job type's user
    command. Config comes from the job conf (``TONY_CONF_PATH``, written
    by ``tony serve``); the stats file path from ``TONY_SERVE_STATS``
    (exported by the executor)."""
    from tony_tpu import constants
    from tony_tpu.conf import TonyConfig

    conf_path = os.environ.get(constants.ENV_CONF_PATH)
    if not conf_path:
        print("[tony-serve-replica] no TONY_CONF_PATH; run under a tony "
              "serve job")
        return 1
    conf = TonyConfig.load(conf_path)
    model_name = conf.get(SERVE_MODEL)
    ckpt_dir = conf.get(SERVE_CKPT_DIR) or conf.get(CKPT_DIR)
    if not model_name or not ckpt_dir:
        print(f"[tony-serve-replica] need {SERVE_MODEL} and "
              f"{SERVE_CKPT_DIR} in the job conf")
        return 1
    mesh = None
    mesh_kw = conf.get(SERVE_MESH)
    if mesh_kw:
        from tony_tpu import parallel as par
        mesh = par.MeshSpec(**json.loads(mesh_kw)).build()
    # Disaggregated role: the executor exports the jobtype
    # (TONY_JOB_NAME), the conf maps jobtype -> role — the per-jobtype
    # role spec `tony serve --role` writes. A classic one-jobtype serve
    # job has no role key and runs colocated.
    job_type = os.environ.get(constants.ENV_JOB_NAME) or "serve"
    role = conf.get(serve_role_key(job_type)) or "colocated"
    # Warm-standby membership is decided HERE, by position: the AM's
    # backfill grants elastic tasks above the jobtype's configured
    # instance count, so an index at-or-past that count with a warm
    # pool configured came up as a standby — it precompiles, donates
    # prefix stems, and waits for the promote RPC. The base gang
    # (index < instances) always starts active.
    warm_conf = conf.get(serve_warm_standby_key(job_type))
    if warm_conf is None:
        warm_conf = conf.get(SERVE_WARM_STANDBY)
    warm_pool = int(warm_conf or 0)
    # QoS plane (tony_tpu.serve.qos): a tenant spec in the conf arms
    # weighted-fair admission budgets; absent, from_conf returns None
    # and the engine runs the untagged path byte-identical to before.
    from tony_tpu.serve.qos import QosPolicy

    qos = QosPolicy.from_conf(conf)
    task_index = int(os.environ.get(constants.ENV_TASK_INDEX) or 0)
    warm_standby = warm_pool > 0 and task_index >= conf.instances(job_type)
    replica = Replica(
        model_name=model_name,
        model_kwargs=json.loads(conf.get(SERVE_MODEL_KWARGS) or "{}"),
        ckpt_dir=ckpt_dir,
        dtype_policy=conf.get(SERVE_DTYPE_POLICY, "bf16"),
        mesh=mesh,
        ctx_max=conf.get_int(SERVE_CTX_MAX, 2048),
        block_size=conf.get_int(SERVE_BLOCK_SIZE, 16),
        max_running=conf.get_int(SERVE_MAX_RUNNING, 16),
        spec_k=conf.get_int(SERVE_SPEC_K, 0),
        draft_model_name=conf.get(SERVE_DRAFT_MODEL),
        draft_model_kwargs=json.loads(
            conf.get(SERVE_DRAFT_MODEL_KWARGS) or "{}"),
        draft_ckpt_dir=conf.get(SERVE_DRAFT_CKPT_DIR),
        ngram_max=conf.get_int(SERVE_DRAFT_NGRAM_MAX, 3),
        prefix_cache=conf.get_bool(SERVE_PREFIX_CACHE, False),
        prefill_chunk=conf.get_int(SERVE_PREFILL_CHUNK, 0) or None,
        role=role,
        host_blocks=conf.get_int(SERVE_HOST_BLOCKS, 0),
        prefix_store=conf.get(SERVE_PREFIX_STORE) or None,
        aot_cache=conf.get(SERVE_AOT_CACHE) or None,
        warm_standby=warm_standby,
        demote_watermark=float(conf.get(SERVE_DEMOTE_WATERMARK) or 0.0),
        demote_batch=conf.get_int(SERVE_DEMOTE_BATCH, 0),
        qos=qos)
    # warm() pad self-tuning (tony_tpu.serve.swap): when the cold-start
    # plane is armed and a history root is configured, precompile the
    # prefill pads earlier serve traffic actually used — the histogram
    # in the SERVE_WINDOW records replaces the caller-named
    # prefill_pads= guess.
    from tony_tpu.conf import HISTORY_LOCATION

    history_dir = conf.get(HISTORY_LOCATION)
    if history_dir and (conf.get(SERVE_AOT_CACHE) or warm_standby):
        replica.tune_warm_pads(history_dir)
    replica.serve_forever(
        port=conf.get_int(SERVE_PORT, 0),
        stats_path=os.environ.get(constants.ENV_SERVE_STATS))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
