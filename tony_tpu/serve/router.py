"""Cross-replica request router: the fleet — not a replica — becomes
the unit of serving throughput.

PR 10 made one replica elastic behind the AM's autoscaler; this module
is the missing front: a gateway-side request router over the live
replica set that decides WHERE each generation runs. Arax's framing
(PAPERS 2305.01291 — work decoupled from concrete accelerator
instances) lands here as three scoring signals per replica, all carried
by telemetry the fleet already ships on the executor heartbeat:

* **prefix-cache overlap** — the router chain-hashes the prompt's KV
  blocks (:mod:`tony_tpu.serve.prefix`, the identical key scheme the
  replica pool uses) and matches them against each replica's advertised
  block digest: a replica already holding the conversation's prefix
  skips that much prefill outright, so overlap is worth real launches,
  not just queue position;
* **load** — queue depth and in-flight occupancy (the autoscaler's
  pressure signals, reused);
* **tail latency** — p99 over the replica's stats window.

Sticky session affinity rides on top: a ``session_id`` pins its
follow-up turns to the replica that served them (which is exactly where
the prefix cache holds the conversation), until that replica retires or
fails — then the router re-dispatches against the scores and re-pins.
Failover is part of dispatch, not an afterthought: a dead replica's
request re-routes to the next-best candidate and the replica is marked
down until a fresh heartbeat revives it.

Jax-free by the same layering rule as ``serve.scaling``: the router
runs on a gateway host (or inside the AM) with no accelerator stack —
transports are pluggable, so tests and benches drive in-process
:class:`~tony_tpu.serve.engine.EngineFront` replicas while production
dials the replica RPC port carried on the heartbeat
(``rpc_port``/host, surfaced through ``session.serve_endpoints`` and
the AM's ``serve_endpoints`` RPC verb).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from tony_tpu.serve import prefix as prefix_mod
from tony_tpu.serve.disagg import HandoffError


def _wire_completion(out: Any, rid: Optional[Any]) -> Dict[str, Any]:
    """Duck-typed completion -> wire dict, ONE definition for every
    dispatch path (the router is jax-free, so it mirrors
    ``engine.Completion.wire`` by shape instead of importing it). RPC
    transports already return the dict."""
    if isinstance(out, dict):
        return out
    return {"rid": getattr(out, "rid", rid),
            "tokens": list(out.tokens),
            "latency_ms": round(1e3 * out.latency_s, 3)}


class NoReplicaError(RuntimeError):
    """Every known replica is retired or down — the fleet cannot take
    the request (surface to the caller as back-pressure, like an
    AdmissionError one level up)."""


@dataclasses.dataclass(frozen=True)
class RouterPolicy:
    """Scoring weights for one route decision. The score is
    ``cache_weight · overlap_fraction − queue_weight · queue_depth −
    p99_weight · p99_seconds`` — overlap is normalized to the prompt's
    block count (a whole-prompt hit is worth ``cache_weight`` no matter
    the prompt length), load terms are raw (one queued request offsets
    a ``1/queue_weight`` overlap fraction). Deliberately linear and
    jax-free: unit-testable like :class:`~tony_tpu.serve.scaling.
    ScalingPolicy`, and the AM glue stays a dumb applier."""
    cache_weight: float = 4.0
    queue_weight: float = 1.0
    p99_weight: float = 0.5
    # A replica whose last heartbeat is older than this is scored as
    # down (dispatch still tries it LAST rather than never — a stale
    # clock must not brick a one-replica fleet).
    stale_s: float = 30.0

    def __post_init__(self):
        if self.cache_weight < 0 or self.queue_weight < 0 \
                or self.p99_weight < 0:
            raise ValueError("router weights must be >= 0, got "
                             f"{self.cache_weight}/{self.queue_weight}/"
                             f"{self.p99_weight}")


@dataclasses.dataclass
class ReplicaView:
    """The router's picture of one replica: identity, transport, and
    the latest heartbeat-derived telemetry."""
    name: str
    address: Optional[str] = None        # host:port of the replica RPC
    client: Optional[Any] = None         # in-process transport override
    queue_depth: float = 0.0
    running: float = 0.0
    p99_ms: float = 0.0
    digest: frozenset = frozenset()
    # Parked-conversation handles (PR 16): the conversations whose KV
    # this replica holds in its host-offload tier — a returning turn
    # re-pinned here resumes without a re-prefill, so the parked set
    # outranks the overlap score for its own conversations.
    parked: frozenset = frozenset()
    # Disaggregated-serving role (tony_tpu.serve.disagg): "prefill" /
    # "decode" replicas split the request into a prefill dispatch and a
    # KV handoff target; "colocated" (every pre-PR 15 replica) serves
    # whole requests.
    role: str = "colocated"
    last_seen: float = 0.0
    alive: bool = True
    retired: bool = False

    def update(self, stats: Dict[str, Any], *, now: float) -> None:
        self.queue_depth = float(stats.get("queue_depth", 0.0) or 0.0)
        self.running = float(stats.get("running", 0.0) or 0.0)
        self.p99_ms = float(stats.get("p99_ms", 0.0) or 0.0)
        digest = stats.get("prefix_digest")
        if digest is not None:
            self.digest = frozenset(str(k) for k in digest)
        parked = stats.get("parked_digest")
        if parked is not None:
            self.parked = frozenset(str(c) for c in parked)
        role = stats.get("role")
        if isinstance(role, str) and role:
            self.role = role
        self.last_seen = now
        self.alive = True


def score(policy: RouterPolicy, view: ReplicaView,
          prompt_keys: Sequence[str]) -> float:
    """One replica's score for one prompt (pure — the unit-test
    surface). Cache overlap counts the longest chain-key PREFIX present
    in the replica's digest: chain keys make an interior match without
    its ancestors useless, so intersection would overcount."""
    overlap = 0.0
    if prompt_keys and view.digest:
        overlap = prefix_mod.match_overlap(prompt_keys, view.digest) \
            / len(prompt_keys)
    return (policy.cache_weight * overlap
            - policy.queue_weight * (view.queue_depth + view.running)
            - policy.p99_weight * view.p99_ms / 1e3)


class RequestRouter:
    """Route + dispatch requests over the elastic replica set.

    Thread-safe. ``block_size`` must match the fleet's engine geometry
    (the chain keys are block-aligned); ``dial`` turns an address into
    a transport for RPC replicas — anything with
    ``generate(tokens, max_new_tokens, rid=...)`` returning an object
    or mapping with a ``tokens`` field works, so in-process
    :class:`~tony_tpu.serve.engine.EngineFront` instances register
    directly via ``client=``.
    """

    def __init__(self, *, block_size: int = 16,
                 policy: Optional[RouterPolicy] = None,
                 dial: Optional[Any] = None,
                 dial_timeout_s: float = 15.0):
        if block_size <= 0:
            raise ValueError(f"need positive block_size, got {block_size}")
        self.block_size = int(block_size)
        self.policy = policy or RouterPolicy()
        # Short transport retry window ON PURPOSE: a dead replica must
        # fail the attempt fast so dispatch can fail over — the long
        # wait belongs to the generation itself, not to redialing a
        # refused connection.
        self.dial_timeout_s = float(dial_timeout_s)
        self._dial = dial or (lambda addr: _rpc_dial(
            addr, self.dial_timeout_s))
        self._lock = threading.Lock()
        self._replicas: Dict[str, ReplicaView] = {}
        self._affinity: Dict[Any, str] = {}
        # Lifetime counters (the router's own stats surface).
        self.dispatched = 0
        self.failovers = 0
        self.affinity_hits = 0
        self.cache_routed = 0            # decisions won on overlap > 0
        self.handoffs = 0                # disaggregated dispatches
        self.handoff_fallbacks = 0       # handoff failed -> colocated
        self.park_pins = 0               # re-pins onto parked KV

    # -- membership --------------------------------------------------------
    def upsert_replica(self, name: str, *, address: Optional[str] = None,
                       client: Optional[Any] = None,
                       stats: Optional[Dict[str, Any]] = None) -> None:
        """Add or refresh one replica (heartbeat ingestion path). A
        refresh revives a down-marked replica — the heartbeat is the
        liveness source of truth, a failed dispatch only a hint."""
        now = time.monotonic()
        with self._lock:
            view = self._replicas.get(name)
            if view is None:
                if address is None and client is None:
                    raise ValueError(f"new replica {name!r} needs an "
                                     f"address or an in-process client")
                view = ReplicaView(name=name)
                self._replicas[name] = view
            if address is not None:
                view.address = address
            if client is not None:
                view.client = client
            view.retired = False
            if stats:
                view.update(stats, now=now)
            else:
                view.last_seen = now
                view.alive = True

    def retire_replica(self, name: str) -> None:
        """Scale-down/teardown: the replica stops receiving new work;
        sessions pinned to it re-route (and re-pin) on their next
        turn."""
        with self._lock:
            view = self._replicas.get(name)
            if view is not None:
                view.retired = True

    def refresh_from_task_infos(self, infos: Sequence[Dict[str, Any]],
                                *, job_type: Optional[str] = None) -> None:
        """Ingest the AM's ``get_task_infos`` wire form (or the
        ``serve_endpoints`` verb's output): live serve tasks whose
        heartbeat carried an ``rpc_port`` become routable replicas at
        ``host:rpc_port``; terminal tasks retire. One call wires the
        router to the whole elastic fleet — scale-ups appear, retired
        replicas drain, no per-replica plumbing. ``job_type`` filters to
        one jobtype; the default ingests every entry — a disaggregated
        fleet's prefill and decode GANGS are separate jobtypes in one
        job (the heterogeneous-gang wiring), and ``serve_endpoints``
        already scopes its output to the serve-role jobtypes."""
        for info in infos:
            jt = info.get("job_type", job_type or "serve")
            if job_type is not None and jt != job_type:
                continue
            name = f"{jt}:{info['index']}"
            metrics = dict(info.get("serve_metrics") or {})
            terminal = info.get("status") in ("SUCCEEDED", "FAILED",
                                              "LOST", "KILLED")
            if terminal:
                self.retire_replica(name)
                continue
            port = metrics.get("rpc_port")
            host = info.get("host")
            if not port or not host:
                continue            # not serving yet (no stats file)
            # Hot-swap down-mark (tony_tpu.serve.swap): a replica
            # inside its swap window advertises swapping=1.0 — retire
            # it for the window so new requests land on the rest of
            # the fleet (warm standbys cover the gap). The swap's
            # immediate post-flip stats republish clears the flag, and
            # the next refresh's upsert revives the replica
            # (retired=False) — no separate re-admit verb.
            if metrics.get("swapping"):
                self.retire_replica(name)
                continue
            self.upsert_replica(name, address=f"{host}:{int(port)}",
                                stats=metrics)

    def replicas(self) -> List[ReplicaView]:
        with self._lock:
            return list(self._replicas.values())

    # -- routing -----------------------------------------------------------
    def route(self, tokens: Sequence[int],
              session_id: Optional[Any] = None) -> str:
        """The replica name for one request — sticky affinity first
        (the session's history lives in that replica's prefix cache),
        then the policy score over live candidates."""
        keys = prefix_mod.chain_keys(tokens, self.block_size)
        with self._lock:
            if session_id is not None:
                pinned = self._replicas.get(
                    self._affinity.get(session_id, ""))
                if pinned is not None and pinned.alive \
                        and not pinned.retired:
                    self.affinity_hits += 1
                    return pinned.name
            live = self._live()
            if not live:
                raise NoReplicaError(
                    f"no live replica among {len(self._replicas)} known")
            if session_id is not None:
                # Affinity missed (router restart, pin dropped on a
                # failover) but a replica still HOLDS the conversation
                # parked in its host tier — re-pin there: a resume
                # skips the whole shared-history prefill, which beats
                # any overlap score the scoring below could produce.
                sid = str(session_id)
                for v in sorted(live, key=lambda v: v.name):
                    if sid in v.parked:
                        self.park_pins += 1
                        self._affinity[session_id] = v.name
                        return v.name
            best = max(live, key=lambda v: (score(self.policy, v, keys),
                                            v.name))
            if keys and best.digest \
                    and prefix_mod.match_overlap(keys, best.digest):
                self.cache_routed += 1
            if session_id is not None:
                self._affinity[session_id] = best.name
            return best.name

    # -- disaggregated routing (tony_tpu.serve.disagg) ---------------------
    def _live(self) -> List[ReplicaView]:
        """THE liveness filter — the one definition :meth:`route`,
        :meth:`route_split`, and the split detection share, so the
        colocated and disaggregated paths can never disagree on which
        replicas are routable. Caller holds the lock."""
        now = time.monotonic()
        live = [v for v in self._replicas.values()
                if v.alive and not v.retired
                and now - v.last_seen <= self.policy.stale_s]
        if not live:
            live = [v for v in self._replicas.values()
                    if v.alive and not v.retired]
        return live

    def _unpin(self, session_id: Any, name: str) -> None:
        """Drop a session pin that references ``name`` (a plain sticky
        pin or either half of a disaggregated pair). Takes the router
        lock itself — call it OUTSIDE a held ``self._lock`` region (the
        lock is not reentrant; the concurrency lint holds this module
        to the discipline)."""
        if session_id is None:
            return
        with self._lock:
            pinned = self._affinity.get(session_id)
            if pinned == name or (isinstance(pinned, tuple)
                                  and name in pinned):
                del self._affinity[session_id]

    def route_split(self, tokens: Sequence[int],
                    session_id: Optional[Any] = None) -> tuple:
        """``(prefill_name, decode_name)`` for one disaggregated
        dispatch, or ``(None, None)`` when the fleet has no live
        prefill+decode split (the caller then runs the colocated PR 13
        path unchanged). Prompts go to the prefill gang scored by
        prefix overlap (the same policy score — a prefill replica's
        published stem blocks are worth skipped launches); the handoff
        target is the decode replica with the shallowest queue. Sticky
        affinity pins the PAIR: the conversation's generated KV lives
        on the decode replica, its prompt-stem blocks on the prefill
        replica that computed them."""
        with self._lock:
            live = self._live()
            if not (any(v.role == "prefill" for v in live)
                    and any(v.role == "decode" for v in live)):
                # The one split-detection site (dispatch relies on it):
                # answered BEFORE the prompt is hashed, so a colocated
                # fleet never pays chain_keys here.
                return None, None
        keys = prefix_mod.chain_keys(tokens, self.block_size)
        with self._lock:
            live = self._live()
            prefills = [v for v in live if v.role == "prefill"]
            decodes = [v for v in live if v.role == "decode"]
            if not prefills or not decodes:
                return None, None
            if session_id is not None:
                pinned = self._affinity.get(session_id)
                if isinstance(pinned, tuple) and len(pinned) == 2:
                    pf = self._replicas.get(pinned[0])
                    dc = self._replicas.get(pinned[1])
                    if pf in prefills and dc in decodes:
                        self.affinity_hits += 1
                        return pf.name, dc.name
            best_pf = max(prefills,
                          key=lambda v: (score(self.policy, v, keys),
                                         v.name))
            best_dc = min(decodes,
                          key=lambda v: (v.queue_depth + v.running,
                                         v.name))
            if keys and best_pf.digest \
                    and prefix_mod.match_overlap(keys, best_pf.digest):
                self.cache_routed += 1
            if session_id is not None:
                self._affinity[session_id] = (best_pf.name, best_dc.name)
            return best_pf.name, best_dc.name

    def _decode_target(self, name: str) -> Any:
        """What the prefill side ships to: the in-process client when
        one is registered, the dialable ``host:port`` otherwise."""
        with self._lock:
            view = self._replicas[name]
            return view.client if view.client is not None \
                else view.address

    def _dispatch_disagg(self, tokens: Sequence[int],
                         max_new_tokens: int, *,
                         session_id: Optional[Any],
                         rid: Optional[Any],
                         max_attempts: int,
                         tenant: Optional[str] = None) -> Dict[str, Any]:
        """Prefill-gang dispatch + KV handoff, with the PR 13 failover
        split kept intact: a TRANSPORT fault (``OSError`` family) marks
        the replica down and re-dispatches; a typed
        :class:`~tony_tpu.serve.disagg.HandoffError` (the decode pool
        rejected the import after the shipper's bounded retries, or the
        PREFILL pool was under transient pressure — prefill_only has no
        queue to park the request in, so the shipper side re-types that
        pressure) falls back to COLOCATED prefill on the decode replica — its engine
        prefills for itself — so one slow importer costs this request a
        fallback, never the prefill gang its throughput. Request-level
        errors (AdmissionError/RpcError) still propagate untouched."""
        last_err: Optional[Exception] = None
        split_gone = False
        # conv rides the handoff payload to the decode engine (and the
        # fallback's colocated generate) — the decode replica is where
        # the conversation's generated KV lives, so it is the one that
        # parks and resumes it. tenant rides the same way (the decode
        # engine is where QoS budgets meter the request); tagless
        # requests ship no kwarg, so older replica stubs keep working.
        kw = {} if session_id is None else {"conv": str(session_id)}
        if tenant is not None:
            kw["tenant"] = str(tenant)
        for _ in range(max(1, int(max_attempts))):
            pf, dc = self.route_split(tokens, session_id)
            if pf is None:
                # The split dissolved (possibly mid-retry — failovers
                # drained a gang): the colocated path owns the rest,
                # whatever already failed; whoever still serves can
                # still take this request whole.
                split_gone = True
                break
            try:
                out = self._client_of(pf).prefill_handoff(
                    [int(t) for t in tokens], int(max_new_tokens),
                    rid=rid, decode=self._decode_target(dc), **kw)
                with self._lock:
                    self.handoffs += 1
            except OSError as e:        # prefill transport fault
                last_err = e
                with self._lock:
                    view = self._replicas.get(pf)
                    if view is not None:
                        view.alive = False
                    self.failovers += 1
                self._unpin(session_id, pf)
                continue
            except HandoffError as e:
                last_err = e
                with self._lock:
                    self.handoff_fallbacks += 1
                try:
                    # A DISTINCT rid for the fallback generation: the
                    # failed handoff may have half-landed (transport
                    # died after the decode side committed the import),
                    # and re-submitting the same rid to the same engine
                    # would collide with the live sequence. The
                    # caller's rid is restored on the response below.
                    out = self._client_of(dc).generate(
                        [int(t) for t in tokens], int(max_new_tokens),
                        rid=None if rid is None else f"{rid}~fallback",
                        **kw)
                except OSError as e2:   # decode transport fault
                    last_err = e2
                    with self._lock:
                        view = self._replicas.get(dc)
                        if view is not None:
                            view.alive = False
                        self.failovers += 1
                    self._unpin(session_id, dc)
                    continue
            with self._lock:
                self.dispatched += 1
            out = _wire_completion(out, rid)
            if rid is not None:
                out["rid"] = rid        # undo a ~fallback rewrite
            out["replica"] = dc
            out["prefill_replica"] = pf
            return out
        if split_gone:
            return self._dispatch_colocated(tokens, max_new_tokens,
                                            session_id=session_id,
                                            rid=rid,
                                            max_attempts=max_attempts,
                                            tenant=tenant)
        raise NoReplicaError(
            f"disaggregated dispatch failed after "
            f"{max_attempts} attempt(s): {last_err}") from last_err

    def _client_of(self, name: str) -> Any:
        with self._lock:
            view = self._replicas[name]
            if view.client is not None:
                return view.client
            return self._dial(view.address)

    def dispatch(self, tokens: Sequence[int], max_new_tokens: int, *,
                 session_id: Optional[Any] = None,
                 rid: Optional[Any] = None,
                 max_attempts: int = 3,
                 tenant: Optional[str] = None) -> Dict[str, Any]:
        """Route + generate with failover: a replica whose TRANSPORT
        fails (dead socket, refused dial — ``OSError`` family) is
        marked down (until its next heartbeat) and the request
        re-dispatches to the next-best candidate — retirement or a
        crash costs the caller a retry, never the request.
        Request-level errors (an ``AdmissionError`` for an oversized
        prompt, an application ``RpcError``) propagate to the caller
        untouched: the replica is healthy, the REQUEST is bad, and
        down-marking on it would let one misbehaving client poison the
        whole fleet.

        Role-aware since PR 15: a fleet running the disaggregated
        prefill/decode split dispatches prompt → prefill gang → KV
        handoff → decode replica (:meth:`route_split`); a colocated
        fleet (or a split that lost a whole gang) runs the PR 13 path
        byte-for-byte unchanged."""
        # route_split itself answers "is there a live split" — (None,
        # None) sends _dispatch_disagg straight down the colocated
        # path — so no separate pre-scan of the fleet is needed here.
        return self._dispatch_disagg(
            tokens, max_new_tokens, session_id=session_id, rid=rid,
            max_attempts=max_attempts, tenant=tenant)

    def _dispatch_colocated(self, tokens: Sequence[int],
                            max_new_tokens: int, *,
                            session_id: Optional[Any] = None,
                            rid: Optional[Any] = None,
                            max_attempts: int = 3,
                            tenant: Optional[str] = None) -> Dict[str, Any]:
        last_err: Optional[Exception] = None
        # The session id doubles as the engine-side conversation handle
        # (conv): a host-tier replica parks the turn's KV under it and
        # the next turn — re-pinned here by affinity or the parked
        # digest — resumes instead of re-prefilling. Sessionless
        # requests ship no kwarg, so pre-PR 16 client stubs keep
        # working unchanged; tenant follows the same optional-kwarg
        # discipline for the QoS plane (tony_tpu.serve.qos).
        kw = {} if session_id is None else {"conv": str(session_id)}
        if tenant is not None:
            kw["tenant"] = str(tenant)
        for _ in range(max(1, int(max_attempts))):
            name = self.route(tokens, session_id)
            try:
                out = self._client_of(name).generate(
                    list(int(t) for t in tokens), int(max_new_tokens),
                    rid=rid, **kw)
            except OSError as e:    # transport fault (ConnectionError,
                last_err = e        # timeout, refused dial, ...)
                with self._lock:
                    view = self._replicas.get(name)
                    if view is not None:
                        view.alive = False
                    self.failovers += 1
                self._unpin(session_id, name)
                continue
            with self._lock:
                self.dispatched += 1
            out = _wire_completion(out, rid)
            out["replica"] = name
            return out
        raise NoReplicaError(
            f"dispatch failed after {max_attempts} attempt(s): "
            f"{last_err}") from last_err

    # -- telemetry ---------------------------------------------------------
    def stats(self) -> Dict[str, float]:
        with self._lock:
            live = sum(1 for v in self._replicas.values()
                       if v.alive and not v.retired)
            return {
                "replicas": float(len(self._replicas)),
                "replicas_live": float(live),
                "dispatched": float(self.dispatched),
                "failovers": float(self.failovers),
                "affinity_hits": float(self.affinity_hits),
                "cache_routed": float(self.cache_routed),
                "handoffs": float(self.handoffs),
                "handoff_fallbacks": float(self.handoff_fallbacks),
                "park_pins": float(self.park_pins),
                "sessions": float(len(self._affinity)),
            }


def _rpc_dial(address: str, timeout: float) -> Any:
    """Default transport: the control-plane JSON-lines RPC client
    against a replica's ``generate``/``prefill_handoff`` verbs (lazy
    import — the RPC stack only loads when a network replica is
    actually dialed)."""
    from tony_tpu.rpc import RpcClient, RpcError

    class _Front:
        def generate(self, tokens, max_new_tokens, rid=None, conv=None,
                     tenant=None):
            with RpcClient(address, timeout=timeout) as client:
                return client.call("generate", tokens=tokens,
                                   max_new_tokens=max_new_tokens,
                                   rid=rid, conv=conv, tenant=tenant)

        def prefill_handoff(self, tokens, max_new_tokens, rid=None,
                            decode=None, conv=None, tenant=None):
            # ``decode`` crosses the wire as an address — the prefill
            # REPLICA ships the fat KV payload replica-to-replica; the
            # router only orchestrates. A transported HandoffError
            # (the JSON-lines wire carries "<TypeName>: <message>")
            # re-types so the router's fallback split keeps working
            # over RPC exactly as in-process.
            try:
                with RpcClient(address, timeout=timeout) as client:
                    return client.call("prefill_handoff", tokens=tokens,
                                       max_new_tokens=max_new_tokens,
                                       rid=rid, decode_address=decode,
                                       conv=conv, tenant=tenant)
            except RpcError as e:
                if str(e).startswith("HandoffError:"):
                    raise HandoffError(str(e), retryable=False) from e
                raise

    return _Front()


class RouterRpcHandler:
    """RPC verbs of one router front (JSON-lines wire, same as the
    AM's and the replica's) — ``generate`` forwards through
    :meth:`RequestRouter.dispatch`, so a gateway client speaks ONE verb
    whether it dials a replica or the fleet."""

    def __init__(self, router: RequestRouter):
        self.router = router

    def rpc_generate(self, tokens: List[int], max_new_tokens: int = 16,
                     rid: Optional[str] = None,
                     session_id: Optional[str] = None,
                     tenant: Optional[str] = None) -> Dict[str, Any]:
        return self.router.dispatch(tokens, max_new_tokens, rid=rid,
                                    session_id=session_id, tenant=tenant)

    def rpc_router_stats(self) -> Dict[str, float]:
        return self.router.stats()


class RouterServer:
    """The fleet's network front door: an RPC server around one
    :class:`RequestRouter`, optionally polling an AM for the live
    replica set (``am_address`` + ``poll_s``) so membership tracks the
    autoscaler with zero manual wiring. Front it with
    :class:`tony_tpu.proxy.ProxyServer` for gateway access, exactly
    like a replica."""

    def __init__(self, router: RequestRouter, *, host: str = "0.0.0.0",
                 port: int = 0, am_address: Optional[str] = None,
                 poll_s: float = 2.0):
        from tony_tpu.rpc import RpcServer

        self.router = router
        self.am_address = am_address
        self.poll_s = float(poll_s)
        self._server = RpcServer(RouterRpcHandler(router), host=host,
                                 port=port)
        self._stop = threading.Event()
        self._stop_lock = threading.Lock()   # guards the stop transition
        self._poller: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.port

    @property
    def address(self) -> str:
        return self._server.address

    def start(self) -> "RouterServer":
        self._server.start()
        if self.am_address:
            # Under the stop lock (the concurrency lint holds this
            # module to its own discipline): a stop() overlapping
            # start() must either see no poller or the whole one — a
            # half-published thread would be joined never.
            with self._stop_lock:
                self._poller = threading.Thread(target=self._poll_loop,
                                                name="tony-router-poll",
                                                daemon=True)
                self._poller.start()
        return self

    def _poll_loop(self) -> None:
        from tony_tpu.rpc import RpcClient

        while not self._stop.wait(self.poll_s):
            try:
                with RpcClient(self.am_address, timeout=5.0) as client:
                    infos = client.call("serve_endpoints")
                self.router.refresh_from_task_infos(infos)
            except Exception:  # noqa: BLE001 — AM mid-restart; re-poll
                pass

    def stop(self) -> None:
        """Deterministic teardown: stop the poller and JOIN it, then
        stop the RPC server (which joins its accept thread). Idempotent
        AND race-free — teardown paths (context exit, CLI finally,
        tests) may overlap, and the loser of the atomic test-and-set
        must no-op rather than shutdown() a closed server or join a
        poller the winner already cleared."""
        with self._stop_lock:
            if self._stop.is_set():
                return
            self._stop.set()
            poller, self._poller = self._poller, None
        if poller is not None:
            poller.join(timeout=2)
        self._server.stop()

    # The explicit-close spelling the shutdown-hygiene audit asks every
    # thread-owning front to have (DeviceIterator.close, RpcClient.close).
    close = stop

    def __enter__(self) -> "RouterServer":
        return self.start()

    def __exit__(self, *exc: object) -> None:
        self.stop()
