"""Persistent prefix store: hot published KV stems on disk, keyed by
chain hash.

The serving fleet's hottest KV bytes are its shared prompt stems (system
prompts, few-shot preambles) — content-addressed by the prefix tier's
chain keys (:mod:`tony_tpu.serve.prefix`), adopted by every conversation
that shares them. But the prefix tier dies with its replica: a fresh
replica, and every scale-up grant the AM launches, re-prefills stems the
fleet computed thousands of times already. This module persists them
through the ckpt plane's commit discipline so a cold replica warms from
disk instead of recompute:

* one directory per stem — ``stem_<tip>/`` where ``<tip>`` is the
  chain's LAST key (chain hashing makes the tip name the whole chain:
  two different prefixes cannot share a tip);
* inside, ``blocks.bin`` (each block's raw k bytes then v bytes,
  concatenated) plus a ``stem.json`` manifest carrying the chain keys,
  the pool geometry header, and a per-block chunk table ``{offset,
  nbytes, k_nbytes, crc32}`` — the ckpt sidecar idiom
  (:mod:`tony_tpu.ckpt.format`), and the CRC is bit-identical to the
  handoff wire's ``crc32(k_bytes + v_bytes)`` (zlib's running-CRC
  identity), so one checksum guards a block from device fetch through
  disk and back;
* commit is stage + atomic rename: payload and manifest are written
  (fsynced) into ``stem_<tip>.tmp`` and ``os.replace``d into place —
  a crashed writer leaves a ``.tmp`` orphan, never a half stem, and
  :meth:`PrefixStore.get` re-verifies every chunk CRC on read.

Jax-free by the same layering rule as ``serve.prefix``: the AM names
the store in a scale-up grant and the replica loads it at startup —
only the latter ever touches a device.
"""

from __future__ import annotations

import base64
import json
import os
import zlib
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

from tony_tpu.ckpt.format import TMP_SUFFIX, _atomic_write_json, _fsync_dir

_PREFIX = "stem_"
FORMAT = "tony-kvstem-v1"


class PrefixStore:
    """One directory of persisted KV stems (see module docstring).

    ``put``/``get`` speak the handoff wire's block payload form —
    ``{"k": b64, "v": b64, "crc": int}`` — so the engine's existing
    export (:meth:`~tony_tpu.serve.kvcache.PagedKVCache.export_keys`)
    and import (:meth:`~tony_tpu.serve.engine.ServeEngine.adopt_stem`)
    paths ARE the store's serialization, CRC discipline included."""

    def __init__(self, root: str) -> None:
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _dir(self, tip: str) -> Path:
        return self.root / f"{_PREFIX}{tip}"

    def stems(self) -> List[str]:
        """Committed stem tips, sorted (``.tmp`` orphans excluded)."""
        out = []
        for entry in sorted(os.listdir(self.root)):
            if entry.startswith(_PREFIX) \
                    and not entry.endswith(TMP_SUFFIX):
                out.append(entry[len(_PREFIX):])
        return out

    def put(self, keys: Sequence[str], blocks: Sequence[Dict[str, Any]],
            header: Dict[str, Any]) -> bool:
        """Persist one stem: ``keys`` the chain, ``blocks`` its wire
        payloads, ``header`` the pool geometry (:meth:`~tony_tpu.serve.
        kvcache.PagedKVCache.wire_header`). Idempotent per tip — a
        committed stem is immutable (same tip = same chain = same
        content) and re-puts return False. Every payload's CRC is
        verified BEFORE any byte lands on disk; a corrupt payload
        raises ``ValueError`` with nothing written."""
        keys = [str(k) for k in keys]
        if not keys or len(keys) != len(blocks):
            raise ValueError(f"stem needs one payload per chain key: "
                             f"{len(keys)} key(s), {len(blocks)} block(s)")
        final = self._dir(keys[-1])
        if final.exists():
            return False
        raws: List[bytes] = []
        table: List[Dict[str, Any]] = []
        offset = 0
        for i, blk in enumerate(blocks):
            kb = base64.b64decode(blk["k"])
            vb = base64.b64decode(blk["v"])
            crc = zlib.crc32(kb + vb) & 0xFFFFFFFF
            if crc != int(blk["crc"]):
                raise ValueError(
                    f"stem block {i} CRC mismatch (got {crc:#010x}, "
                    f"payload claims {int(blk['crc']):#010x}) — "
                    f"refusing to persist corrupt KV")
            raws.append(kb + vb)
            table.append({"offset": offset, "nbytes": len(kb) + len(vb),
                          "k_nbytes": len(kb), "crc32": crc})
            offset += len(kb) + len(vb)
        staging = Path(f"{final}{TMP_SUFFIX}")
        staging.mkdir(parents=True, exist_ok=True)
        with open(staging / "blocks.bin", "wb") as f:
            for raw in raws:
                f.write(raw)
            f.flush()
            os.fsync(f.fileno())
        _atomic_write_json(staging / "stem.json", {
            "format": FORMAT, "keys": keys,
            "header": dict(header), "chunks": table})
        os.replace(staging, final)
        _fsync_dir(self.root)
        return True

    def get(self, tip: str) -> Optional[Dict[str, Any]]:
        """Load one stem back into wire form: ``{"keys": [...],
        "header": {...}, "blocks": [wire payloads]}`` — ready for
        ``adopt_stem``. Every chunk CRC re-verifies on read (the
        ChunkReader discipline); a corrupt or missing stem returns
        ``None`` — the store is a warm-start cache, and a bad entry
        means recompute, never a crash."""
        d = self._dir(tip)
        try:
            with open(d / "stem.json") as f:
                manifest = json.load(f)
            if manifest.get("format") != FORMAT:
                return None
            blocks: List[Dict[str, Any]] = []
            with open(d / "blocks.bin", "rb") as f:
                for chunk in manifest["chunks"]:
                    f.seek(int(chunk["offset"]))
                    raw = f.read(int(chunk["nbytes"]))
                    if len(raw) != int(chunk["nbytes"]) or \
                            (zlib.crc32(raw) & 0xFFFFFFFF) \
                            != int(chunk["crc32"]):
                        return None
                    kn = int(chunk["k_nbytes"])
                    blocks.append({
                        "k": base64.b64encode(raw[:kn]).decode("ascii"),
                        "v": base64.b64encode(raw[kn:]).decode("ascii"),
                        "crc": int(chunk["crc32"])})
            return {"keys": list(manifest["keys"]),
                    "header": dict(manifest["header"]),
                    "blocks": blocks}
        except (OSError, ValueError, KeyError, TypeError):
            return None
