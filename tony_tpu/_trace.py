"""Trace-side profiler recording shared by the overlap, ckpt, and input
planes. One shim instead of a per-module copy: the import is lazy (the
calling planes stay importable without the profiler stack) and every
failure is swallowed (bookkeeping must never sink a step or a save).
Failures past a successful import get the log-once-per-registry
diagnostics in :func:`tony_tpu.profiler.safe_record`; a failure of the
import itself is logged once here — otherwise a broken profiler wiring
would silently drop every record forever.
"""

from __future__ import annotations

import logging

_logger = logging.getLogger(__name__)
_import_warned = False


def trace_record(kind: str, tag: str, /, **fields) -> None:
    # kind/tag are positional-only: the unified collective schema puts a
    # "kind" field in **fields and must not collide with the registry
    # selector.
    global _import_warned
    try:
        from tony_tpu import profiler
        record = profiler.safe_record   # never raises past this point
    except Exception:  # noqa: BLE001
        if not _import_warned:
            _import_warned = True
            _logger.debug("profiler unavailable; dropping %r records",
                          kind, exc_info=True)
        return
    record(kind, tag, **fields)
