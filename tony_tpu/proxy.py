"""TCP proxy: gateway access to in-cluster notebook/TensorBoard ports.

Mirrors ``tony-proxy``'s ``ProxyServer`` (upstream ``tony-proxy/src/main/
java/``, ≈200 LoC, unverified — SURVEY.md §0/§2.2): a dumb bidirectional TCP
port-forwarder so a user on the gateway host can reach a port that only
exists inside the cluster network (the notebook container, a TensorBoard).
"""

from __future__ import annotations

import socket
import threading
from typing import Optional

_BUF = 65536


def _pump(src: socket.socket, dst: socket.socket) -> None:
    """Relay src→dst until EOF, then propagate the FIN with a half-close of
    dst's write side only — the other direction may still be mid-response
    (TCP half-close semantics; a full SHUT_RDWR here would truncate it)."""
    try:
        while True:
            data = src.recv(_BUF)
            if not data:
                break
            dst.sendall(data)
    except OSError:
        pass
    finally:
        try:
            dst.shutdown(socket.SHUT_WR)
        except OSError:
            pass


def _relay(client: socket.socket, upstream: socket.socket) -> None:
    """Run both pump directions; close the sockets only when both are done."""
    t = threading.Thread(target=_pump, args=(upstream, client), daemon=True)
    t.start()
    _pump(client, upstream)
    t.join()
    for s in (client, upstream):
        try:
            s.close()
        except OSError:
            pass


class ProxyServer:
    """Forward ``localhost:local_port`` → ``remote_host:remote_port``."""

    def __init__(self, remote_host: str, remote_port: int,
                 local_host: str = "127.0.0.1", local_port: int = 0):
        self.remote = (remote_host, int(remote_port))
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((local_host, local_port))
        self._listener.listen(16)
        self.local_host, self.local_port = self._listener.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="tony-proxy", daemon=True)

    def start(self) -> "ProxyServer":
        self._thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                return
            try:
                upstream = socket.create_connection(self.remote, timeout=10)
            except OSError:
                client.close()
                continue
            threading.Thread(target=_relay, args=(client, upstream),
                             daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        self._thread.join(timeout=2)

    def __enter__(self) -> "ProxyServer":
        return self.start()

    def __exit__(self, *exc: object) -> None:
        self.stop()
