"""TaskExecutor: the in-container bootstrap around the user process.

Mirrors ``com.linkedin.tony.TaskExecutor`` + ``TaskMonitor`` (upstream
``tony-core/src/main/java/com/linkedin/tony/TaskExecutor.java`` ≈600 LoC /
``TaskMonitor.java`` ≈400 LoC, unverified — SURVEY.md §0, call stack §3.2).
Sequence, faithfully carried over:

1. read the AM→executor env contract (job type, index, AM address, conf path);
2. reserve the rendezvous port (and the TensorBoard port when the adapter
   asks) via a held listening socket — the reference's ``ServerSocket`` trick;
3. ``register_worker_spec`` over RPC;
4. poll ``get_cluster_spec`` until the AM has ALL registrations (gang barrier);
5. build the framework env via the runtime adapter (``TF_CONFIG``, the JAX
   coordinator triple, …), localize ``src_dir`` into the container workdir;
6. release the reserved sockets, fork the user process, pump its output to
   the container log;
7. heartbeat + metrics threads while the user process runs;
8. ``register_execution_result`` and exit with the user's exit code.

The metrics monitor samples ``/proc`` (cpu%/rss) instead of parsing
``nvidia-smi`` — chip utilization on TPU comes from the profiler hook, not a
sidecar CLI.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

from tony_tpu import chaos, constants
from tony_tpu import conf as conf_mod
from tony_tpu import util
from tony_tpu.conf import TonyConfig
from tony_tpu.rpc import ENV_JOB_TOKEN, RpcClient
from tony_tpu.runtime import TaskContext, get_framework


def _proc_descendants(root: int) -> list:
    """All live descendant pids of ``root``, via one /proc scan. Callers
    must kill ``root`` before this list so a supervising parent can't
    respawn children mid-sweep."""
    children: Dict[int, list] = {}
    for p in Path("/proc").glob("[0-9]*"):
        try:
            stat = (p / "stat").read_text()
        except OSError:
            continue
        # field 4 (after the parenthesised comm, which may contain spaces)
        ppid = int(stat.rsplit(")", 1)[1].split()[1])
        children.setdefault(ppid, []).append(int(p.name))
    out, stack = [], [root]
    while stack:
        for c in children.get(stack.pop(), []):
            out.append(c)
            stack.append(c)
    return out


def _link_tree(src: Path, dest: Path, symlinks: bool = False) -> None:
    """copytree that hardlinks file content instead of copying (falls back
    to a real copy across filesystems). Venvs run to GBs and localization
    is per-container — a byte copy per container is the dominant cost in
    the submit→all-running latency (SURVEY.md §7 hard part #4); links make
    it metadata-only. ONLY for trees used read-only by convention (the
    venv): an in-place write through a hardlink would mutate the staged
    copy and every sibling container. src trees keep real copies — user
    code freely writes into its own src dir."""
    def _link(s, d, **kw):
        try:
            os.link(s, d)
        except OSError:           # cross-device, perms, or FS without links
            shutil.copy2(s, d)

    shutil.copytree(src, dest, symlinks=symlinks, copy_function=_link)


def read_serve_stats(path: str | Path) -> Optional[Dict[str, object]]:
    """The replica engine's published telemetry (qps/p99_ms/queue_depth
    — see ``ServeEngine.write_stats``), or None. Scalars normalize to
    float; the router's ``prefix_digest`` (a list of block chain-keys)
    passes through as a string list. Jax-free and failure-silent by
    contract: this rides the heartbeat loop, and a torn/absent/garbage
    stats file must never sink liveness."""
    try:
        with open(path) as fh:
            raw = json.load(fh)
        return util.normalize_serve_telemetry(raw)
    except Exception:   # noqa: BLE001 — advisory telemetry only
        return None


def reserve_port(host: str = "") -> socket.socket:
    """Bind a listening socket on an ephemeral port and keep it open —
    the reference's ServerSocket reservation. Caller closes just before the
    user process needs to bind the port itself."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host, 0))
    s.listen(1)
    return s


class TaskMonitor:
    """Samples the user process from /proc on ``tony.task.metrics-interval-ms``
    and ships ``{cpu_pct, rss_mb, uptime_s}`` to the AM (reference:
    ``TaskMonitor`` → ``MetricsRpc``)."""

    def __init__(self, pid: int, client: RpcClient, job_type: str, index: int,
                 interval_s: float):
        self.pid = pid
        self.client = client
        self.job_type = job_type
        self.index = index
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="task-monitor")
        self._start_time = time.monotonic()
        self._last_cpu: Optional[tuple[float, float]] = None  # (cpu_s, wall)

    def start(self) -> None:
        self._thread.start()

    def sample(self) -> Optional[Dict[str, float]]:
        try:
            with open(f"/proc/{self.pid}/stat") as f:
                fields = f.read().rsplit(") ", 1)[1].split()
            utime, stime = int(fields[11]), int(fields[12])
            with open(f"/proc/{self.pid}/statm") as f:
                rss_pages = int(f.read().split()[1])
        except (OSError, IndexError, ValueError):
            return None
        hz = os.sysconf("SC_CLK_TCK")
        page = os.sysconf("SC_PAGE_SIZE")
        cpu_s = (utime + stime) / hz
        now = time.monotonic()
        cpu_pct = 0.0
        if self._last_cpu is not None:
            prev_cpu, prev_wall = self._last_cpu
            dt = now - prev_wall
            if dt > 0:
                cpu_pct = 100.0 * (cpu_s - prev_cpu) / dt
        self._last_cpu = (cpu_s, now)
        return {
            "cpu_pct": round(cpu_pct, 2),
            "rss_mb": round(rss_pages * page / (1024 * 1024), 2),
            "uptime_s": round(now - self._start_time, 2),
        }

    def _run(self) -> None:
        # A failed report must not kill the monitor: during an AM-relaunch
        # window every metrics RPC fails transiently, and dying here would
        # silence metrics for the rest of the job (the heartbeat loop
        # tolerates the same outage). Back off exponentially while the AM
        # is unreachable, resume the normal cadence on the first success.
        backoff = 0.0
        while not self._stop.wait(self.interval_s + backoff):
            m = self.sample()
            if m is None:
                return  # user process exited; nothing left to sample
            try:
                self.client.call("metrics_report", job_type=self.job_type,
                                 index=self.index, metrics=m)
                backoff = 0.0
            except Exception:
                backoff = min(60.0, max(self.interval_s, backoff * 2))

    def stop(self, join_timeout_s: float = 2.0) -> None:
        """Signal and JOIN (bounded): the monitor shares the executor's
        RPC client, and teardown closing that client under a mid-call
        sampler was a race, not a shutdown. The monitor's own RPC window
        is short; a stuck call is abandoned at the timeout rather than
        wedging executor exit."""
        self._stop.set()
        if self._thread.is_alive() \
                and self._thread is not threading.current_thread():
            self._thread.join(timeout=join_timeout_s)


class TaskExecutor:
    """One executor lifecycle; :meth:`run` returns the exit code to die with."""

    def __init__(self, env: Optional[Dict[str, str]] = None):
        e = env if env is not None else os.environ
        self.job_type = e[constants.ENV_JOB_NAME]
        self.index = int(e[constants.ENV_TASK_INDEX])
        self.am_address = e[constants.ENV_AM_ADDRESS]
        self.app_id = e.get(constants.ENV_APP_ID, "app_unknown")
        self.attempt_id = int(e.get(constants.ENV_ATTEMPT_ID, "1"))
        self.conf = TonyConfig.load(e[constants.ENV_CONF_PATH])
        self.host = e.get("TONY_EXECUTOR_HOST", "127.0.0.1")
        self.src_dir = e.get(constants.ENV_SRC_DIR) or None
        self.venv_path = e.get(constants.ENV_VENV) or None
        self.resources_dir = e.get(constants.ENV_RESOURCES_DIR) or None
        self.log_dir = Path(e.get(constants.ENV_LOG_DIR, "."))
        self.token = e.get(ENV_JOB_TOKEN) or None
        self.client = RpcClient(self.am_address, token=self.token,
                                timeout=60.0)
        self.framework = get_framework(
            self.conf.get(conf_mod.APPLICATION_FRAMEWORK, "jax"))
        self.user_proc: Optional[subprocess.Popen] = None
        self._am_lost = False
        self._hb_stop = threading.Event()

    # -- pieces ------------------------------------------------------------
    def serve_stats_path(self) -> Path:
        """The per-container serving-telemetry file: the executor
        exports this path (``TONY_SERVE_STATS``) into the user env, a
        serve replica's engine publishes into it, and the heartbeat
        loop piggybacks whatever appears there to the AM."""
        return self.log_dir / "serve-stats.json"

    def drain_file_path(self) -> Path:
        """The per-container drain flag: the executor exports this path
        (``TONY_DRAIN_FILE``) into the user env and CREATES the file when
        the AM's heartbeat reply carries the drain directive; train_loop
        polls for it between steps and exits EXIT_DRAINED after a
        synchronous commit. A file, not a signal: the user process may be
        several forks deep, and the drain must reach the training loop —
        not whatever shell happens to be the direct child."""
        return self.log_dir / "drain"

    def user_command(self) -> str:
        cmd = (self.conf.get(conf_mod.command_key(self.job_type))
               or self.conf.get("tony.application.executes"))
        if not cmd:
            raise RuntimeError(
                f"no command for task {self.job_type}:{self.index}: set "
                f"tony.application.executes or tony.{self.job_type}.command")
        return cmd

    def localize_src(self) -> Optional[Path]:
        """Per-container copy of the staged src dir (reference:
        ``LocalizableResource`` download into the container sandbox)."""
        if not self.src_dir or not Path(self.src_dir).is_dir():
            return None
        dest = Path.cwd() / "src"
        if not dest.exists():
            shutil.copytree(self.src_dir, dest)
        return dest

    def localize_venv(self) -> Optional[Path]:
        """Localize the staged venv (dir or archive) into the container
        sandbox (reference: the venv zip in the YARN LocalResource map)."""
        if not self.venv_path:
            return None
        src = Path(self.venv_path)
        dest = Path.cwd() / "venv"
        if dest.exists():
            return dest
        if src.is_dir():
            # link vs copy: see conf.VENV_LOCALIZATION — links alias the
            # staged inodes, so in-place writers must opt into "copy".
            mode = (self.conf.get(conf_mod.VENV_LOCALIZATION) or "link")
            if mode == "copy":
                shutil.copytree(src, dest, symlinks=True)
            else:
                _link_tree(src, dest, symlinks=True)
        elif src.is_file():
            shutil.unpack_archive(str(src), str(dest))
            # Archives often wrap a single top-level dir: flatten to it.
            entries = list(dest.iterdir())
            if len(entries) == 1 and entries[0].is_dir() \
                    and (entries[0] / "bin").is_dir():
                dest = entries[0]
        else:
            return None
        return dest

    def localize_resources(self, dest: Path) -> None:
        """Localize ``tony.containers.resources`` entries into the user
        process cwd (reference: the YARN ``LocalResource`` map built by
        ``Utils.uploadFileAndSetConfResources`` / ``LocalizableResource``).
        Entries are resolved by basename against the staged resources dir
        (``TONY_RESOURCES_DIR``) — the conf carries client-side staged
        paths that need not exist on a remote worker. ``#archive`` entries
        are unpacked in place of copied."""
        entries = self.conf.get_list(conf_mod.CONTAINERS_RESOURCES)
        for entry in entries:
            path_s, _, flag = entry.partition("#")
            name = Path(path_s).name
            src = (Path(self.resources_dir) / name if self.resources_dir
                   else Path(path_s))
            if not src.exists():
                raise RuntimeError(
                    f"container resource {name!r} not found "
                    f"(resources dir: {self.resources_dir})")
            # Resources OVERWRITE same-named files in the cwd: they
            # localize after the src copy, and a stale src-shipped file
            # silently shadowing the declared resource is the worse bug.
            target = dest / name
            if flag == "archive":
                shutil.unpack_archive(str(src), str(dest))
            elif src.is_dir():
                shutil.copytree(src, target, symlinks=True,
                                dirs_exist_ok=True)
            else:
                shutil.copy2(src, target)

    def _venv_env(self, venv: Optional[Path]) -> Dict[str, str]:
        """PATH/VIRTUAL_ENV entries so ``python`` in the user command
        resolves inside the shipped venv; ``tony.application.python-binary``
        (absolute, or relative to the venv) takes precedence."""
        out: Dict[str, str] = {}
        paths = []
        pybin = self.conf.get(conf_mod.PYTHON_BINARY)
        if pybin:
            p = Path(pybin)
            if not p.is_absolute() and venv is not None:
                p = venv / p
            paths.append(str(p.parent))
        if venv is not None:
            out["VIRTUAL_ENV"] = str(venv)
            paths.append(str(venv / "bin"))
        if paths:
            out["PATH"] = os.pathsep.join(
                paths + [os.environ.get("PATH", "")])
        return out

    def _heartbeat_loop(self, interval_s: float,
                        max_failures: int = 5) -> None:
        """Heartbeat to the AM; after ``max_failures`` CONSECUTIVE failed
        calls the AM is presumed dead and the user process is killed —
        the container-side half of AM-attempt restart (reference: the NM
        tears down containers when the application terminates). Without
        this, an AM crash would orphan executors training forever.

        Uses its own short-timeout RPC client: the shared ``self.client``
        retries transport errors internally for its full 30s window, which
        would stretch ``max_failures`` consecutive misses into minutes.

        When the job configures ``tony.ckpt.dir``, each heartbeat also
        carries the last COMMITTED checkpoint step found there (a cheap
        committed-dir scan — the manifest rename is the commit point, so
        listing is race-free): the AM logs per attempt what a gang restart
        will resume from. The scan must never sink liveness — any failure
        degrades to reporting nothing."""
        hb_client = RpcClient(self.am_address, token=self.token,
                              timeout=max(1.0, interval_s))
        ckpt_dir = self.conf.get(conf_mod.CKPT_DIR) or None
        serve_stats_path = self.serve_stats_path()
        drain_path = self.drain_file_path()

        def ckpt_step() -> Optional[int]:
            if not ckpt_dir:
                return None
            try:
                # format, not the package: the package import pulls the
                # snapshot/restore stack (jax) the executor doesn't need.
                from tony_tpu.ckpt.format import latest_step
                return latest_step(ckpt_dir)
            except Exception:   # noqa: BLE001 — advisory telemetry only
                return None

        def published() -> Optional[Dict[str, object]]:
            # Publication pointer announcement (tony_tpu.publish): the
            # beat carries the ckpt root's published.json version/step
            # so the AM's rolling fleet swap learns of a new pointer
            # from ANY gang member's heartbeat — no extra RPC, no AM
            # filesystem dependency. latest_publication is jax-free and
            # failure-silent by contract, same as the ckpt_step scan.
            if not ckpt_dir:
                return None
            try:
                from tony_tpu.publish import latest_publication
                rec = latest_publication(ckpt_dir)
                if rec is None:
                    return None
                return {"version": rec["version"], "step": rec["step"]}
            except Exception:   # noqa: BLE001 — advisory telemetry only
                return None

        failures = 0
        try:
            while not self._hb_stop.wait(interval_s):
                if chaos.drop_heartbeat():
                    # Injected silence: the AM sees missed heartbeats, the
                    # executor stays healthy — the lost-task path under test.
                    continue
                try:
                    step = ckpt_step()
                    serve = read_serve_stats(serve_stats_path) \
                        if serve_stats_path.is_file() else None
                    extras: Dict[str, object] = {}
                    if step is not None:
                        extras["ckpt_step"] = step
                    if serve is not None:
                        extras["serve"] = serve
                    pub = published()
                    if pub is not None:
                        extras["published"] = pub
                    resp = hb_client.call("heartbeat", job_type=self.job_type,
                                          index=self.index, **extras)
                    failures = 0
                    if isinstance(resp, dict) and resp.get("drain"):
                        try:
                            drain_path.touch()
                        except OSError:
                            pass  # retried on the next beat; never fatal
                    if self._am_lost and self.user_proc is None:
                        # The AM was only transiently unreachable (e.g. a
                        # relaunch window) and recovered before launch —
                        # un-stick the flag so run() doesn't abort a task
                        # whose AM is demonstrably alive again.
                        print("[tony-executor] AM reachable again before "
                              "launch; resuming", file=sys.stderr)
                        self._am_lost = False
                except Exception:
                    failures += 1
                    if failures < max_failures:
                        continue
                    if self._hb_stop.is_set():
                        return
                    if not self._am_lost:
                        print(f"[tony-executor] AM unreachable for "
                              f"{failures} heartbeats; terminating task",
                              file=sys.stderr)
                        self._am_lost = True
                    if self.user_proc is None:
                        # Not launched yet (gang barrier / localization):
                        # run() aborts before launch on _am_lost; keep
                        # polling in case the launch raced this check.
                        continue
                    self._kill_user_proc()
                    return
        finally:
            hb_client.close()

    def _kill_user_proc(self) -> None:
        """SIGKILL the user process TREE. The command runs via a shell
        that does not exec (dash keeps `sh -c` as the parent), and user
        code may fork — killing only the direct child leaves the real
        workload alive. The tree is walked via /proc rather than killpg:
        the user proc shares the executor's process group (the scheduler's
        teardown killpg depends on that), so a group kill would take the
        executor down with it."""
        if self.user_proc is None or self.user_proc.poll() is not None:
            return
        # Root FIRST: a supervising parent (e.g. a retry-loop shell) could
        # otherwise fork a replacement child between the /proc scan and
        # its own kill; dead parents can't respawn. A supervisor DEEPER in
        # the tree can still fork between the scan and its own kill, so
        # re-scan and sweep until no new live descendants appear (bounded:
        # each pass only finds children of processes killed in the prior
        # pass, so the tree depth bounds the real iteration count).
        root = self.user_proc.pid
        targets = [root] + _proc_descendants(root)
        killed: set = set()
        for _ in range(5):
            for pid in targets:
                try:
                    os.kill(pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                killed.add(pid)
            targets = [p for p in _proc_descendants(root) if p not in killed]
            if not targets:
                break

    def run(self) -> int:
        conf = self.conf
        # 1-2. reserve ports.
        rendezvous_sock = reserve_port()
        port = rendezvous_sock.getsockname()[1]
        adapter = self.framework.task_adapter()
        pre_ctx = TaskContext(conf=conf, job_type=self.job_type,
                              index=self.index, cluster_spec={},
                              am_address=self.am_address, app_id=self.app_id,
                              attempt_id=self.attempt_id)
        tb_sock = None
        tb_port = None
        if adapter.need_reserve_tb_port(pre_ctx):
            tb_sock = reserve_port()
            tb_port = tb_sock.getsockname()[1]
        prof_sock = None
        prof_port = None
        if adapter.need_reserve_profiler_port(pre_ctx):
            prof_sock = reserve_port()
            prof_port = prof_sock.getsockname()[1]
        # 3. register.
        self.client.call("register_worker_spec", job_type=self.job_type,
                         index=self.index, host=self.host, port=port)
        # 4. gang barrier.
        gang_timeout_s = conf.get_int(conf_mod.AM_GANG_TIMEOUT_MS, 120000) / 1e3
        deadline = time.monotonic() + gang_timeout_s
        hb_interval_s = conf.get_int(
            conf_mod.TASK_HEARTBEAT_INTERVAL_MS, 1000) / 1e3
        max_missed = self.conf.get_int(
            conf_mod.TASK_MAX_MISSED_HEARTBEATS, 25)
        hb_thread = threading.Thread(
            target=self._heartbeat_loop,
            args=(hb_interval_s, max(3, max_missed)),
            daemon=True, name="heartbeat")
        hb_thread.start()
        try:
            while True:
                # Clamp the RPC window to the barrier's remaining budget:
                # with the client's default 60s retry window, one call
                # begun just before the deadline could overshoot the gang
                # timeout by a full minute.
                remaining = max(0.5, deadline - time.monotonic())
                try:
                    resp = self.client.call("get_cluster_spec",
                                            _timeout=min(10.0, remaining))
                    last_err = None
                except (ConnectionError, OSError) as e:
                    resp, last_err = None, e  # transient; deadline decides
                if resp is not None and resp["complete"]:
                    cluster_spec = resp["spec"]
                    callback_info = resp.get("callback_info", {})
                    break
                if time.monotonic() > deadline:
                    cause = f"; last RPC error: {last_err}" if last_err \
                        else ""
                    print(f"[tony-executor] gang barrier timed out after "
                          f"{gang_timeout_s:.0f}s{cause}", file=sys.stderr)
                    return constants.EXIT_FAILURE
                time.sleep(0.1)
            # 5. build env + localize.
            ctx = TaskContext(conf=conf, job_type=self.job_type,
                              index=self.index, cluster_spec=cluster_spec,
                              am_address=self.am_address, app_id=self.app_id,
                              attempt_id=self.attempt_id, tb_port=tb_port,
                              profiler_port=prof_port,
                              callback_info=callback_info)
            adapter.validate(ctx)
            task_env = adapter.build_task_env(ctx)
            src = self.localize_src()
            cmd = self.user_command()
            env = dict(os.environ)
            env.update(self._venv_env(self.localize_venv()))
            env.update(task_env)
            env[constants.ENV_SERVE_STATS] = str(
                self.serve_stats_path().resolve())
            drain_path = self.drain_file_path()
            try:
                # Incremental-grant reuse relaunches into this same sandbox:
                # a drain flag left by the PREVIOUS drain must not instantly
                # drain the fresh worker.
                drain_path.unlink()
            except OSError:
                pass
            env[constants.ENV_DRAIN_FILE] = str(drain_path.resolve())
            if self.token:
                env[ENV_JOB_TOKEN] = self.token
            cwd = str(src) if src else os.getcwd()
            self.localize_resources(Path(cwd))
            pypath = [p for p in (cwd, env.get("PYTHONPATH")) if p]
            env["PYTHONPATH"] = os.pathsep.join(pypath)
            # 6. release reserved ports, launch the user process.
            if self._am_lost:
                # AM died while we were still in the barrier/localization
                # phase — launching now would create an unmonitored orphan.
                print("[tony-executor] AM lost before launch; aborting",
                      file=sys.stderr)
                return constants.EXIT_FAILURE
            rendezvous_sock.close()
            if tb_sock is not None:
                tb_sock.close()
            if prof_sock is not None:
                prof_sock.close()
            stdout = open(self.log_dir / constants.USER_STDOUT_NAME, "ab")
            stderr = open(self.log_dir / constants.USER_STDERR_NAME, "ab")
            # Stays in the executor's process group on purpose: the
            # scheduler's teardown killpg must keep reaping executor +
            # user tree together; the executor's own kills walk the tree
            # (see _kill_user_proc).
            self.user_proc = subprocess.Popen(
                cmd, shell=True, env=env, cwd=cwd,
                stdout=stdout, stderr=stderr)
            stdout.close()
            stderr.close()
            if tb_port is not None and self.job_type in (
                    constants.TENSORBOARD, constants.NOTEBOOK,
                    *constants.CHIEF_LIKE_JOB_TYPES):
                try:
                    self.client.call("register_tensorboard_url",
                                     url=f"http://{self.host}:{tb_port}")
                except Exception:
                    pass
            # Push framework callback info to the AM adapter (reference:
            # registerCallbackInfo → receiveTaskCallbackInfo): the bound
            # profiler endpoint, so the AM knows where each rank's
            # jax.profiler server listens.
            if constants.ENV_PROFILER_PORT in task_env:
                try:
                    self.client.call(
                        "register_callback_info",
                        task_id=f"{self.job_type}:{self.index}",
                        payload=json.dumps({"profiler": (
                            f"{self.host}:"
                            f"{task_env[constants.ENV_PROFILER_PORT]}")}))
                except Exception:
                    pass
            # 7. metrics monitor.
            metrics_interval_s = conf.get_int(
                conf_mod.TASK_METRICS_INTERVAL_MS, 5000) / 1e3
            monitor = TaskMonitor(self.user_proc.pid, self.client,
                                  self.job_type, self.index,
                                  metrics_interval_s)
            monitor.start()
            # 8. wait (with optional execution timeout), report, exit.
            timeout_ms = conf.get_int(
                conf_mod.TASK_EXECUTOR_EXECUTION_TIMEOUT_MS, 0)
            diagnostics = ""
            try:
                exit_code = self.user_proc.wait(
                    timeout=timeout_ms / 1e3 if timeout_ms else None)
            except subprocess.TimeoutExpired:
                self._kill_user_proc()
                self.user_proc.wait()
                exit_code = constants.EXIT_FAILURE
                diagnostics = f"execution timed out after {timeout_ms}ms"
            if self._am_lost and not diagnostics:
                diagnostics = "AM unreachable; task terminated by executor"
            monitor.stop()
            if self._am_lost:
                # The AM is gone — reporting would only burn the RPC
                # client's full retry window before failing anyway.
                print(f"[tony-executor] skipping result RPC: {diagnostics}",
                      file=sys.stderr)
                return exit_code
            try:
                self.client.call("register_execution_result",
                                 job_type=self.job_type, index=self.index,
                                 exit_code=exit_code, diagnostics=diagnostics)
            except Exception as e:
                print(f"[tony-executor] result RPC failed: {e}",
                      file=sys.stderr)
            return exit_code
        finally:
            self._hb_stop.set()
            # Bounded join so teardown is deterministic, not
            # daemon-abandoned: the loop's own RPC window is short
            # (timeout = heartbeat interval), so a live thread exits
            # within one wait tick; a wedged one is abandoned rather
            # than blocking executor exit.
            hb_thread.join(timeout=5.0)
            for s in (rendezvous_sock, tb_sock, prof_sock):
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
            self._kill_user_proc()
            self.client.close()


def main() -> int:
    try:
        executor = TaskExecutor()
    except Exception as e:  # bad env/conf: fail loudly before any RPC
        print(f"[tony-executor] bootstrap failed: {e}", file=sys.stderr)
        return constants.EXIT_FAILURE
    # Forward SIGTERM (scheduler stop) to the user process so it can die fast.
    def _on_term(signum, frame):
        executor._kill_user_proc()
        sys.exit(constants.EXIT_KILLED)
    signal.signal(signal.SIGTERM, _on_term)
    return executor.run()
