"""``python -m tony_tpu.executor`` — the container entry point (reference:
``TaskExecutor.main``, launched by the NM per ``buildContainerLaunchContext``)."""

import sys

from tony_tpu.executor import main

if __name__ == "__main__":
    sys.exit(main())
