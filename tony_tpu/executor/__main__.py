"""``python -m tony_tpu.executor`` — the container entry point (reference:
``TaskExecutor.main``, launched by the NM per ``buildContainerLaunchContext``)."""

import sys

from tony_tpu.util import restore_site_dirs

restore_site_dirs()   # -S entry: see tony_tpu.util.ENV_SITE_DIRS

from tony_tpu.executor import main

if __name__ == "__main__":
    sys.exit(main())
