"""User-facing rendezvous helper for JAX jobs launched by TonY-TPU.

The JAXRuntime exports the coordinator triple (SURVEY.md §2.4 "rendezvous");
user code simply calls::

    import tony_tpu.distributed as dist
    dist.initialize()          # no-op outside a TonY job or for 1 process

which forwards to ``jax.distributed.initialize(coordinator_address,
num_processes, process_id)`` — the TPU-native replacement for ``TF_CONFIG`` /
c10d / Gloo rendezvous.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

from tony_tpu import constants


def env_spec() -> Optional[tuple[str, int, int]]:
    """(coordinator_address, num_processes, process_id) from the executor env,
    or None when not running under TonY-TPU."""
    addr = os.environ.get(constants.ENV_COORDINATOR_ADDRESS)
    n = os.environ.get(constants.ENV_NUM_PROCESSES)
    pid = os.environ.get(constants.ENV_PROCESS_ID)
    if not addr or n is None or pid is None:
        return None
    return addr, int(n), int(pid)


def initialize(local_device_ids: Optional[Sequence[int]] = None) -> bool:
    """Bring up the JAX coordination service from TonY env. Returns True if
    multi-process init happened, False for the single-process fallback.
    Also starts the per-task profiler server when the JAXRuntime enabled it
    (``tony.task.profiler.enabled`` — SURVEY.md §5.1)."""
    _maybe_start_profiler()
    spec = env_spec()
    if spec is None:
        return False
    addr, num_processes, process_id = spec
    if num_processes <= 1:
        return False
    import jax
    if local_device_ids is None:
        raw = os.environ.get(constants.ENV_LOCAL_DEVICE_IDS)
        if raw:
            local_device_ids = [int(x) for x in raw.split(",")]
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    return True


def _maybe_start_profiler() -> None:
    """``jax.profiler.start_server`` on the port the JAXRuntime assigned —
    reachable through ``tony proxy``/TensorBoard for live traces."""
    port = os.environ.get(constants.ENV_PROFILER_PORT)
    if not port:
        return
    import jax
    try:
        jax.profiler.start_server(int(port))
    except Exception:  # pragma: no cover — port race; profiling is advisory
        pass


def process_id() -> int:
    spec = env_spec()
    return spec[2] if spec else 0


def num_processes() -> int:
    spec = env_spec()
    return spec[1] if spec else 1
