"""In-AM job state: task registry, cluster-spec assembly, success policy.

Mirrors ``com.linkedin.tony.TonySession`` / ``TonySession.TonyTask`` /
``TaskStatus`` (upstream ``tony-core/src/main/java/com/linkedin/tony/
TonySession.java``, unverified — SURVEY.md §0).  The subtle part carried over
faithfully is the **success-policy matrix** (SURVEY.md §7 "hard parts" #2):

* *untracked* job types (``ps``/``tensorboard``/``notebook``…) never affect the
  final status and are torn down when the job completes;
* if a *chief-like* task (``chief``/``master``) exists, its completion ends the
  job with its exit code ("stop on chief done");
* otherwise the job succeeds when **all tracked** tasks exit 0, and (with
  fail-fast on, the default) fails on the first tracked non-zero exit;
* a task that misses too many heartbeats is marked LOST and fails the job.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from tony_tpu import constants
from tony_tpu import util
from tony_tpu.conf import TonyConfig


class TaskStatus(enum.Enum):
    """Lifecycle of one task (reference: ``TonySession.TaskStatus``)."""
    NEW = "NEW"                  # declared in config, no container yet
    REQUESTED = "REQUESTED"      # container requested from the scheduler
    ALLOCATED = "ALLOCATED"      # container granted, executor launching
    REGISTERED = "REGISTERED"    # executor called registerWorkerSpec
    RUNNING = "RUNNING"          # gang barrier passed, user process running
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    LOST = "LOST"                # missed-heartbeat expiry
    KILLED = "KILLED"            # torn down (untracked at job end, or preempted)
    DRAINED = "DRAINED"          # clean elastic-resize exit (committed + left)

    @property
    def is_terminal(self) -> bool:
        return self in (TaskStatus.SUCCEEDED, TaskStatus.FAILED,
                        TaskStatus.LOST, TaskStatus.KILLED,
                        TaskStatus.DRAINED)


class JobStatus(enum.Enum):
    """Final-status of the whole application (reference: ``FinalApplicationStatus``)."""
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    KILLED = "KILLED"


class TonyTask:
    """One (job_type, index) task and its container/executor state."""

    def __init__(self, job_type: str, index: int, tracked: bool,
                 elastic: bool = False):
        self.job_type = job_type
        self.index = index
        self.tracked = tracked
        # Elastic tasks are added AFTER the session was built (the serve
        # plane's replica scale-up): they never gate the gang barrier —
        # the original gang's cluster spec is already sealed — and they
        # are the only scale-DOWN victims, so the conf-declared floor
        # stays intact.
        self.elastic = elastic
        self._status = TaskStatus.NEW
        # Every status this task has held, in order (wire-visible via
        # to_info): the client's monitor poll is sampled, so a fast
        # worker can pass REGISTERED→RUNNING→SUCCEEDED between polls —
        # the history lets the monitor print every transition it
        # missed instead of silently skipping RUNNING.
        self.status_history: List[str] = [TaskStatus.NEW.value]
        self.host: Optional[str] = None
        self.port: Optional[int] = None          # rendezvous port registered by executor
        self.container_id: Optional[str] = None
        self.exit_code: Optional[int] = None
        self.diagnostics: str = ""
        self.last_heartbeat: float = 0.0
        self.start_time: float = 0.0
        self.end_time: float = 0.0
        self.preemption_retries = 0
        # Last checkpoint step this task reported committed (heartbeat
        # piggyback; None until a tony.ckpt.dir executor reports one).
        self.ckpt_step: Optional[int] = None
        # Latest weight-publication pointer this task's heartbeat
        # announced ({"version": int, "step": int} — tony_tpu.publish):
        # the AM's rolling fleet swap reads the max version across
        # tasks as its target. None until a publication exists.
        self.published: Optional[Dict[str, int]] = None
        # Latest serving telemetry this task piggybacked on its
        # heartbeat (qps / p99_ms / queue_depth / prefix_cache_hit_rate
        # / blocks_shared / prefill_chunks, plus the router's
        # prefix_digest key list and rpc_port — tony_tpu.serve): what
        # the AM's replica autoscaler and the request router decide on.
        self.serve_metrics: Dict[str, object] = {}
        self.metrics: Dict[str, float] = {}
        # Timeline of TaskMonitor samples (reference: the per-task metric
        # history MetricsRpc accumulates for the portal). Bounded: at the
        # cap, every other sample is dropped so coverage stays full-span.
        self.metrics_history: List[Dict[str, float]] = []

    METRICS_HISTORY_CAP = 512

    @property
    def status(self) -> TaskStatus:
        return self._status

    @status.setter
    def status(self, value: TaskStatus) -> None:
        self._status = value
        if self.status_history[-1] != value.value:
            self.status_history.append(value.value)

    def record_metrics(self, metrics: Dict[str, float]) -> Dict[str, float]:
        """Record one TaskMonitor sample; returns the normalized sample."""
        sample = {str(k): float(v) for k, v in metrics.items()}
        self.metrics.update(sample)
        self.metrics_history.append({"ts": time.time(), **sample})
        if len(self.metrics_history) > self.METRICS_HISTORY_CAP:
            # Thin odd indices: keeps both the span start and the sample
            # appended just above.
            del self.metrics_history[1::2]
        return sample

    @property
    def task_id(self) -> str:
        return f"{self.job_type}:{self.index}"

    @property
    def spec(self) -> Optional[str]:
        if self.host is None or self.port is None:
            return None
        return f"{self.host}:{self.port}"

    def touch(self) -> None:
        self.last_heartbeat = time.monotonic()

    def to_info(self) -> Dict[str, object]:
        """Wire form served over ``getTaskInfos`` (reference: ``TaskInfo``)."""
        return {
            "job_type": self.job_type,
            "index": self.index,
            "status": self.status.value,
            "status_history": list(self.status_history),
            "host": self.host,
            "port": self.port,
            "tracked": self.tracked,
            "exit_code": self.exit_code,
            "diagnostics": self.diagnostics,
            "ckpt_step": self.ckpt_step,
            "published": dict(self.published) if self.published else None,
            "elastic": self.elastic,
            "serve_metrics": dict(self.serve_metrics),
            "metrics": dict(self.metrics),
            "metrics_samples": len(self.metrics_history),
        }

    def __repr__(self) -> str:
        return f"TonyTask({self.task_id}, {self.status.value})"


class TonySession:
    """Thread-safe task registry + job-final-status logic.

    Built once per AM attempt from the effective config (reference:
    ``TonySession.Builder``); the AM drives transitions, the RPC service reads
    and writes under :attr:`lock`.
    """

    def __init__(self, conf: TonyConfig, app_id: str, attempt_id: int = 1):
        self.conf = conf
        self.app_id = app_id
        self.attempt_id = attempt_id
        self.lock = threading.RLock()
        self.job_status = JobStatus.RUNNING
        self.final_message = ""
        self.tensorboard_url: Optional[str] = None
        # Executor-pushed framework info by task_id (registerCallbackInfo).
        self.task_callback_info: Dict[str, str] = {}
        # submit → all-RUNNING latency, set by the AM when the gang barrier
        # passes (BASELINE.md secondary metric).
        self.all_running_latency_s: Optional[float] = None
        # Elastic-resize drain (tony_tpu.am.resize): while True, the
        # heartbeat response tells every live task to commit-and-exit,
        # and the success policy holds its verdict — the resize
        # controller, not task completion, decides what happens next.
        self._draining = False
        self._tasks: Dict[Tuple[str, int], TonyTask] = {}
        untracked = set(conf.untracked_job_types())
        for jt in conf.job_types():
            for i in range(conf.instances(jt)):
                self._tasks[(jt, i)] = TonyTask(jt, i, tracked=jt not in untracked)

    # -- registry ----------------------------------------------------------
    def task(self, job_type: str, index: int) -> TonyTask:
        with self.lock:
            key = (job_type, int(index))
            if key not in self._tasks:
                raise KeyError(f"unknown task {job_type}:{index}")
            return self._tasks[key]

    def tasks(self) -> List[TonyTask]:
        with self.lock:
            return list(self._tasks.values())

    def tracked_tasks(self) -> List[TonyTask]:
        return [t for t in self.tasks() if t.tracked]

    def untracked_tasks(self) -> List[TonyTask]:
        return [t for t in self.tasks() if not t.tracked]

    def task_by_container(self, container_id: str) -> Optional[TonyTask]:
        with self.lock:
            for t in self._tasks.values():
                if t.container_id == container_id:
                    return t
        return None

    def __iter__(self) -> Iterator[TonyTask]:
        return iter(self.tasks())

    # -- cluster spec (gang barrier) ---------------------------------------
    def all_registered(self) -> bool:
        """True once every task has called registerWorkerSpec — the gang
        barrier after which executors may start user processes. Elastic
        tasks (added after the session was built) never gate it: the
        original gang's spec is sealed, and a scale-up replica must not
        re-open the barrier for anyone."""
        with self.lock:
            return all(t.spec is not None for t in self._tasks.values()
                       if not t.elastic)

    def cluster_spec(self) -> Dict[str, List[str]]:
        """``{job_type: ["host:port", ...]}`` ordered by task index
        (reference: ``TonySession#getClusterSpec``)."""
        with self.lock:
            spec: Dict[str, List[str]] = {}
            for jt in self.conf.job_types():
                members = []
                for i in range(self.conf.instances(jt)):
                    t = self._tasks[(jt, i)]
                    members.append(t.spec or "")
                spec[jt] = members
            return spec

    # -- global rank assignment (TPU-native addition) ----------------------
    def global_rank(self, job_type: str, index: int) -> int:
        """Deterministic dense rank over rendezvous tasks (sidecars excluded),
        ordered (job_types(), index). Used by JAXRuntime for ``process_id``
        and by the PyTorch/Horovod adapters for RANK/HOROVOD_RANK. Must match
        ``TaskContext.global_rank``."""
        rank = 0
        for jt in self.conf.job_types():
            if jt in constants.SIDECAR_JOB_TYPES:
                continue
            n = self.conf.instances(jt)
            if jt == job_type:
                if not (0 <= index < n):
                    raise KeyError(f"unknown task {job_type}:{index}")
                return rank + index
            rank += n
        raise KeyError(f"unknown job type {job_type}")

    def num_tasks(self) -> int:
        with self.lock:
            return len(self._tasks)

    # -- transitions driven by RPC/AM --------------------------------------
    def on_registered(self, job_type: str, index: int, host: str, port: int) -> TonyTask:
        with self.lock:
            t = self.task(job_type, index)
            t.host, t.port = host, int(port)
            if not t.status.is_terminal:
                t.status = TaskStatus.REGISTERED
            t.touch()
            return t

    def on_running(self) -> None:
        """Gang barrier passed: mark all registered tasks RUNNING."""
        with self.lock:
            now = time.monotonic()
            for t in self._tasks.values():
                if t.status == TaskStatus.REGISTERED:
                    t.status = TaskStatus.RUNNING
                    t.start_time = t.start_time or now

    def on_heartbeat(self, job_type: str, index: int,
                     ckpt_step: Optional[int] = None,
                     serve: Optional[Dict[str, float]] = None,
                     published: Optional[Dict[str, int]] = None) -> None:
        t = self.task(job_type, index)
        t.touch()
        if ckpt_step is not None:
            t.ckpt_step = int(ckpt_step)
        if serve:
            try:
                t.serve_metrics = util.normalize_serve_telemetry(serve)
            except (TypeError, ValueError):
                pass          # malformed telemetry must not sink liveness
        if published:
            try:
                t.published = {"version": int(published["version"]),
                               "step": int(published["step"])}
            except (TypeError, ValueError, KeyError):
                pass          # same contract: advisory, never liveness

    # -- elastic replica scaling (tony_tpu.serve) --------------------------
    def add_task(self, job_type: str) -> TonyTask:
        """Append one ELASTIC task to ``job_type`` (the AM's replica
        scale-up): next free index, flagged so it never gates the gang
        barrier and is the preferred scale-down victim."""
        with self.lock:
            indices = [i for (jt, i) in self._tasks if jt == job_type]
            if not indices:
                raise KeyError(f"unknown job type {job_type!r}")
            idx = max(indices) + 1
            task = TonyTask(job_type, idx,
                            tracked=self.conf.is_tracked(job_type),
                            elastic=True)
            self._tasks[(job_type, idx)] = task
            return task

    def mark_scaled_down(self, task: TonyTask, reason: str) -> None:
        """Terminal KILLED without failing the job — the deliberate
        scale-down exit (vs LOST/FAILED, which trip the success
        policy)."""
        with self.lock:
            if task.status.is_terminal:
                return
            task.status = TaskStatus.KILLED
            task.exit_code = constants.EXIT_KILLED
            task.diagnostics = reason
            task.end_time = time.monotonic()

    def serve_samples(self, job_type: str) -> List[Dict[str, float]]:
        """Latest serve telemetry per live replica of ``job_type`` —
        the autoscaler's decision input."""
        with self.lock:
            return [dict(t.serve_metrics) for t in self._tasks.values()
                    if t.job_type == job_type and not t.status.is_terminal
                    and t.serve_metrics]

    def serve_job_types(self) -> List[str]:
        """Every jobtype serving traffic: the classic ``serve`` type
        plus any jobtype carrying a ``tony.serve.role.<jobtype>`` conf
        key (the disaggregated prefill/decode gangs — heterogeneous
        jobtypes of ONE job, tony_tpu.serve.disagg)."""
        from tony_tpu.conf import serve_role_key

        out = []
        for jt in self.conf.job_types():
            if jt == constants.SERVE or self.conf.get(serve_role_key(jt)):
                out.append(jt)
        return out

    def serve_endpoints(self, job_type: Optional[str] = None
                        ) -> List[Dict[str, object]]:
        """Wire form of every serving replica that has reported
        telemetry — what the request router
        (:mod:`tony_tpu.serve.router`) ingests to track the elastic
        fleet: live replicas whose heartbeat carried an ``rpc_port``
        become routable at ``host:rpc_port``; terminal entries ride
        along so the router retires them. ``job_type=None`` (the
        default since the disaggregated split) spans every serve-role
        jobtype, so one poll wires the router to the prefill AND decode
        gangs; a named jobtype scopes to it. Live warm STANDBYS
        (heartbeating ``warm_standby`` — the cold-start plane's
        compiled-and-idle pool) are excluded: a standby is capacity,
        not an endpoint, until the AM promotes it; its terminal entry
        still rides along so the router retires it."""
        jts = [job_type] if job_type is not None \
            else self.serve_job_types()
        with self.lock:
            return [t.to_info() for t in self._tasks.values()
                    if t.job_type in jts
                    and (t.serve_metrics or t.status.is_terminal)
                    and not (t.serve_metrics.get("warm_standby")
                             and not t.status.is_terminal)]

    # -- elastic-resize drain (tony_tpu.am.resize) -------------------------
    def request_drain(self) -> None:
        """Arm the drain directive: every subsequent heartbeat response
        carries it, and the success policy freezes until the resize
        controller rules (clean drains must not read as job success)."""
        with self.lock:
            self._draining = True

    def clear_drain(self) -> None:
        with self.lock:
            self._draining = False

    @property
    def draining(self) -> bool:
        with self.lock:
            return self._draining

    def drain_pending(self, job_type: str, index: int) -> bool:
        """Should this task's heartbeat response carry the drain
        directive? True for any live task while a drain is armed."""
        with self.lock:
            if not self._draining:
                return False
            try:
                t = self.task(job_type, index)
            except KeyError:
                return False
            return not t.status.is_terminal

    def drain_complete(self, job_type: str) -> bool:
        """True once every tracked task of ``job_type`` is terminal —
        the DRAINING phase's completion predicate."""
        with self.lock:
            gang = [t for t in self._tasks.values()
                    if t.job_type == job_type and t.tracked]
            return bool(gang) and all(t.status.is_terminal for t in gang)

    def last_committed_step(self) -> Optional[int]:
        """Newest checkpoint step any executor has reported committed —
        what the next attempt will resume from (commit is global: process
        0 renames the manifest only after every process's shards landed,
        so ANY reporter reflects the gang-wide durable state)."""
        with self.lock:
            steps = [t.ckpt_step for t in self._tasks.values()
                     if t.ckpt_step is not None]
            return max(steps) if steps else None

    def on_task_result(self, job_type: str, index: int, exit_code: int,
                       diagnostics: str = "") -> TonyTask:
        with self.lock:
            t = self.task(job_type, index)
            if t.status.is_terminal:
                return t
            t.exit_code = int(exit_code)
            t.diagnostics = diagnostics
            t.end_time = time.monotonic()
            if exit_code == 0:
                t.status = TaskStatus.SUCCEEDED
            elif exit_code == constants.EXIT_DRAINED:
                # Clean elastic-resize exit: the task committed its
                # model+cursor and left on request — terminal, but
                # neither a success nor a failure of the job.
                t.status = TaskStatus.DRAINED
            else:
                t.status = TaskStatus.FAILED
            self._update_job_status()
            return t

    def on_task_lost(self, task: TonyTask, diagnostics: str) -> None:
        with self.lock:
            if task.status.is_terminal:
                return
            task.status = TaskStatus.LOST
            task.exit_code = constants.EXIT_LOST_TASK
            task.diagnostics = diagnostics
            task.end_time = time.monotonic()
            self._update_job_status()

    def kill_remaining(self, reason: str) -> List[TonyTask]:
        """Mark all non-terminal tasks KILLED (untracked teardown at job end,
        or client-initiated kill). Returns the tasks transitioned."""
        with self.lock:
            killed = []
            for t in self._tasks.values():
                if not t.status.is_terminal:
                    t.status = TaskStatus.KILLED
                    t.exit_code = constants.EXIT_KILLED
                    t.diagnostics = reason
                    t.end_time = time.monotonic()
                    killed.append(t)
            return killed

    # -- success policy ----------------------------------------------------
    def _chief_tasks(self) -> List[TonyTask]:
        """All tracked chief-like tasks, in (CHIEF_LIKE_JOB_TYPES, index)
        order. Plural on purpose: ``chief.instances=2`` or chief+master
        configs make every one of them decide the job, not just the first."""
        out = []
        for jt in constants.CHIEF_LIKE_JOB_TYPES:
            for (t_jt, _i), t in sorted(self._tasks.items()):
                if t_jt == jt and t.tracked:
                    out.append(t)
        return out

    def _update_job_status(self) -> None:
        """Re-derive the job status after any tracked-task transition.
        Callers hold :attr:`lock`; the re-entrant re-acquisition here
        costs nothing and makes the guard LEXICAL, so the concurrency
        lint (analysis.concurrency) flags any future job_status write
        that forgets the lock instead of trusting the docstring."""
        with self.lock:
            if self.job_status != JobStatus.RUNNING:
                return
            if self._draining:
                # Mid-resize: tasks are SUPPOSED to go terminal (drained
                # survivors, the preempted victim). The resize controller
                # owns the verdict; a frozen success policy can never
                # misread a drained gang as a finished job.
                return
            fail_fast = self.conf.get_bool(
                "tony.application.fail-fast", True)
            chiefs = self._chief_tasks()
            if chiefs:
                # Chief-done policy: the chiefs' exits decide the job. A
                # failed chief fails the job immediately; success requires
                # all chiefs. If no chief has decided yet, fall through so
                # fail-fast on other tracked tasks still applies while the
                # chief runs.
                failed_chief = next(
                    (c for c in chiefs if c.status.is_terminal
                     and c.status != TaskStatus.SUCCEEDED), None)
                if failed_chief is not None:
                    self.job_status = JobStatus.FAILED
                    self.final_message = (
                        f"chief {failed_chief.task_id} "
                        f"{failed_chief.status.value}: "
                        f"{failed_chief.diagnostics}")
                    return
                if all(c.status == TaskStatus.SUCCEEDED for c in chiefs):
                    self.job_status = JobStatus.SUCCEEDED
                    self.final_message = "chief completed successfully"
                    return
            tracked = [t for t in self._tasks.values() if t.tracked]
            failed = [t for t in tracked
                      if t.status in (TaskStatus.FAILED, TaskStatus.LOST)]
            if failed and fail_fast:
                t = failed[0]
                self.job_status = JobStatus.FAILED
                self.final_message = (
                    f"task {t.task_id} {t.status.value} "
                    f"(exit={t.exit_code}): {t.diagnostics}")
                return
            if tracked and all(t.status.is_terminal for t in tracked):
                if failed:
                    t = failed[0]
                    self.job_status = JobStatus.FAILED
                    self.final_message = (
                        f"{len(failed)}/{len(tracked)} tracked tasks "
                        f"failed; first: {t.task_id} exit={t.exit_code}")
                else:
                    self.job_status = JobStatus.SUCCEEDED
                    self.final_message = (
                        "all tracked tasks completed successfully")

    def is_done(self) -> bool:
        with self.lock:
            return self.job_status != JobStatus.RUNNING

    def task_infos(self) -> List[Dict[str, object]]:
        return [t.to_info() for t in self.tasks()]
