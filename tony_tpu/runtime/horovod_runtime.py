"""Horovod-semantics runtime: ring-allreduce jobs on TPU (reference:
``runtime/HorovodRuntime.java`` + ``runtime/horovod/HorovodDriver.java``).

AM side: once the gang barrier passes (:meth:`on_all_registered`), the adapter
computes Horovod slot assignments from the ordered per-rank host list and
publishes them through an in-AM rendezvous server
(:class:`~tony_tpu.runtime.horovod_driver.HorovodDriver`); the driver address
ships to executors in the cluster-spec callback info.

Executor side: exports the full ``HOROVOD_*`` env (controller, rendezvous
addr/port, rank/size, local and cross ranks) — so user scripts written against
``hvd.init()``-style APIs see the contract they expect. The data plane,
though, is XLA ``psum`` over ICI (the NCCL→ICI replacement named in the north
star): the coordinator triple is exported too, so the same job can run
``tony_tpu.distributed.initialize()`` and use ``jax.lax.psum`` as its
allreduce.
"""

from __future__ import annotations

from typing import Dict, Optional

from tony_tpu import constants
from tony_tpu.runtime import ApplicationMasterAdapter, Framework, TaskContext
from tony_tpu.runtime.base import MLGenericTaskAdapter
from tony_tpu.runtime.horovod_driver import HorovodDriver

CALLBACK_RENDEZVOUS_ADDR = "horovod.rendezvous.address"


class HorovodAMAdapter(ApplicationMasterAdapter):
    def __init__(self) -> None:
        self.driver: Optional[HorovodDriver] = None

    def validate_and_update_config(self, conf) -> None:
        # Idempotent: validation may run more than once per AM attempt and a
        # repeated call must not leak the previous listener socket/thread.
        if self.driver is None:
            self.driver = HorovodDriver()

    def on_all_registered(self) -> None:
        hosts = []
        spec = self.session.cluster_spec()
        for jt in self.session.conf.job_types():
            if jt in constants.SIDECAR_JOB_TYPES:
                continue
            for member in spec.get(jt, []):
                hosts.append(member.rsplit(":", 1)[0])
        assert self.driver is not None
        self.driver.set_hosts(hosts)

    def callback_info(self) -> Dict[str, str]:
        assert self.driver is not None
        return {CALLBACK_RENDEZVOUS_ADDR: self.driver.address}

    def stop(self) -> None:
        if self.driver is not None:
            self.driver.stop()


class HorovodTaskAdapter(MLGenericTaskAdapter):
    def framework_env(self, ctx: TaskContext) -> Dict[str, str]:
        if ctx.is_sidecar():
            # Sidecars hold no Horovod slot and must not inflate HOROVOD_SIZE.
            return {}
        rank = ctx.global_rank()
        n = ctx.num_cluster_tasks()
        local_rank, local_size = ctx.local_rank()
        # cross rank: index of this host among distinct hosts, host-major.
        distinct = []
        for jt in ctx.ml_job_types():
            for spec in ctx.cluster_spec.get(jt, []):
                h = spec.rsplit(":", 1)[0]
                if h not in distinct:
                    distinct.append(h)
        rendezvous = ctx.callback_info.get(CALLBACK_RENDEZVOUS_ADDR, "")
        r_host, _, r_port = rendezvous.rpartition(":")
        env = {
            constants.ENV_HOROVOD_CONTROLLER: "tony",     # ref: "gloo"
            constants.ENV_HOROVOD_RENDEZVOUS_ADDR: r_host,
            constants.ENV_HOROVOD_RENDEZVOUS_PORT: r_port,
            constants.ENV_HOROVOD_RANK: str(rank),
            constants.ENV_HOROVOD_SIZE: str(n),
            constants.ENV_HOROVOD_LOCAL_RANK: str(local_rank),
            constants.ENV_HOROVOD_LOCAL_SIZE: str(local_size),
            constants.ENV_HOROVOD_CROSS_RANK: str(distinct.index(ctx.my_host())),
            constants.ENV_HOROVOD_CROSS_SIZE: str(len(distinct)),
            # NCCL→ICI: same job can bring up the JAX data plane directly.
            constants.ENV_COORDINATOR_ADDRESS: ctx.rank0_spec(),
            constants.ENV_PROCESS_ID: str(rank),
            constants.ENV_NUM_PROCESSES: str(n),
        }
        return env


class HorovodFramework(Framework):
    name = "horovod"

    def am_adapter(self) -> HorovodAMAdapter:
        return HorovodAMAdapter()

    def task_adapter(self) -> HorovodTaskAdapter:
        return HorovodTaskAdapter()
