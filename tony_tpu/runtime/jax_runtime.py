"""JAXRuntime — the first-class TPU-native runtime (BASELINE.json north star).

Replaces the reference's NCCL rendezvous runtimes: the AM assigns roles, and
this adapter wires ``jax.distributed.initialize(coordinator_address,
num_processes, process_id)`` from them. The global-rank-0 task's registered
host:port becomes the coordinator address (its executor reserved that port at
registration, exactly like the reference's ServerSocket reservation in
``TaskExecutor``). The data plane is XLA collectives (``psum`` /
``all_gather`` / ``ppermute`` / ``reduce_scatter``) over ICI intra-slice and
DCN across slices — there is no NCCL and no parameter server.

On a real TPU pod the adapter additionally injects the libtpu topology env
(``TPU_WORKER_ID``, ``TPU_WORKER_HOSTNAMES``, chip pinning via
``TPU_VISIBLE_DEVICES`` when ``tony.<jobtype>.tpus`` subdivides a host) so
multiple tasks can share a host, each seeing only its chips.

User code calls :func:`tony_tpu.distributed.initialize` (or passes the env
straight to ``jax.distributed.initialize``) and then uses plain
``jax.sharding`` meshes.
"""

from __future__ import annotations

from typing import Dict

from tony_tpu import constants
from tony_tpu import conf as conf_mod
from tony_tpu.runtime import ApplicationMasterAdapter, Framework, TaskContext
from tony_tpu.runtime.base import MLGenericTaskAdapter

# Chip-count → rectangular libtpu bounds "x,y,z" for the chip grids TPU
# hosts actually expose (v4: 4 chips 2x2; v5e: 1/4/8 chips; v5p: 4).
_TOPOLOGY_BOUNDS = {1: (1, 1, 1), 2: (1, 2, 1), 4: (2, 2, 1), 8: (2, 4, 1)}


class JAXTaskAdapter(MLGenericTaskAdapter):
    def need_reserve_profiler_port(self, ctx: TaskContext) -> bool:
        return (not ctx.is_sidecar()
                and ctx.conf.get_bool("tony.task.profiler.enabled", False))

    def framework_env(self, ctx: TaskContext) -> Dict[str, str]:
        if ctx.is_sidecar():
            # Sidecars (tensorboard/notebook/driver) are not part of the SPMD
            # world: no coordinator triple, no chip pinning — exporting them
            # would make jax.distributed.initialize wait on a process that
            # never joins.
            return {}
        coordinator = ctx.rank0_spec()
        rank = ctx.global_rank()
        n = ctx.num_cluster_tasks()
        env = {
            constants.ENV_COORDINATOR_ADDRESS: coordinator,
            constants.ENV_PROCESS_ID: str(rank),
            constants.ENV_NUM_PROCESSES: str(n),
        }
        tpus = ctx.conf.get_int(f"tony.{ctx.job_type}.tpus", 0)
        if tpus > 0:
            # Chip pinning: tasks sharing a host each see a disjoint chip
            # set. The offset is the cumulative chip count of lower-ranked
            # co-hosted tasks (each sized by its OWN job type's tpus), so
            # mixed-tpus cohorts neither overlap nor leave gaps.
            first = sum(ctx.conf.get_int(f"tony.{jt}.tpus", 0)
                        for r, jt in ctx.host_cohort() if r < rank)
            chips = ",".join(str(first + i) for i in range(tpus))
            env[constants.ENV_TPU_VISIBLE_DEVICES] = chips
            env[constants.ENV_LOCAL_DEVICE_IDS] = chips
        # libtpu multi-host topology (harmless off-pod; required on pods).
        # The documented contract (pinned by unit test — untestable on a
        # 1-chip host, VERDICT r4 weak #3):
        #  * TPU_WORKER_ID is the PER-HOST worker id and
        #    TPU_WORKER_HOSTNAMES has one entry per HOST, not per task;
        #  * tasks subdividing a host additionally need the process-grid
        #    env (TPU_PROCESS_BOUNDS / TPU_CHIPS_PER_PROCESS_BOUNDS /
        #    TPU_PROCESS_ADDRESSES / TPU_PROCESS_PORT / CLOUD_TPU_TASK_ID),
        #    expressible only when every co-hosted task asks the same chip
        #    count (libtpu's grids are rectangular; a mixed-tpus cohort has
        #    no legal encoding, so only the chip pinning above is emitted).
        hosts: list[str] = []
        for jt in ctx.ml_job_types():
            for spec in ctx.cluster_spec.get(jt, []):
                h = spec.rsplit(":", 1)[0] if spec else ""
                if h not in hosts:
                    hosts.append(h)
        env[constants.ENV_TPU_WORKER_ID] = str(hosts.index(ctx.my_host()))
        env[constants.ENV_TPU_WORKER_HOSTNAMES] = ",".join(hosts)
        local_rank, local_size = ctx.local_rank()
        if tpus > 0 and local_size > 1:
            # Every process must emit the SAME grid env or libtpu init
            # hangs — so the gate is computed from the global cluster
            # spec, identically on every task: all hosts must carry the
            # same task count and every task the same chip ask, else no
            # host emits bounds (an irregular packing has no rectangular
            # encoding).
            per_host: dict = {}
            rank_i = 0
            for jt in ctx.ml_job_types():
                for spec in ctx.cluster_spec.get(jt, []):
                    hh = spec.rsplit(":", 1)[0] if spec else ""
                    per_host.setdefault(hh, []).append((rank_i, jt))
                    rank_i += 1
            host_sizes = {len(v) for v in per_host.values()}
            cohort_tpus = {ctx.conf.get_int(f"tony.{jt}.tpus", 0)
                           for v in per_host.values() for _r, jt in v}
            # Ranks must also be host-CONTIGUOUS: the rectangular grid
            # assumes co-hosted processes hold adjacent task ids; an
            # interleaved placement has no legal encoding either.
            contiguous = all(
                [r for r, _jt in v] == list(range(v[0][0],
                                                  v[0][0] + len(v)))
                for v in per_host.values())
            chip_b = _TOPOLOGY_BOUNDS.get(tpus)
            host_b = _TOPOLOGY_BOUNDS.get(tpus * local_size)
            if (host_sizes == {local_size} and cohort_tpus == {tpus}
                    and contiguous and chip_b and host_b):
                proc_b = (host_b[0] // chip_b[0], host_b[1] // chip_b[1],
                          len(hosts))
                env[constants.ENV_TPU_CHIPS_PER_PROCESS_BOUNDS] = \
                    ",".join(map(str, chip_b))
                env[constants.ENV_TPU_PROCESS_BOUNDS] = \
                    ",".join(map(str, proc_b))
                # Deterministic per-rank ports: every process must know all
                # peers' libtpu addresses BEFORE launch, so these cannot be
                # executor-reserved ephemerals; base+global_rank is unique
                # within the job, and the base is conf-keyed so concurrent
                # jobs sharing hosts can be kept apart.
                base = ctx.conf.get_int(conf_mod.LIBTPU_PORT_BASE, 8476)
                addrs, r = [], 0
                for jt in ctx.ml_job_types():
                    for spec in ctx.cluster_spec.get(jt, []):
                        h = spec.rsplit(":", 1)[0] if spec else ""
                        addrs.append(f"{h}:{base + r}")
                        r += 1
                env[constants.ENV_TPU_PROCESS_ADDRESSES] = ",".join(addrs)
                env[constants.ENV_TPU_PROCESS_PORT] = str(base + rank)
                env[constants.ENV_CLOUD_TPU_TASK_ID] = str(rank)
        # Multi-slice (tony.jax.slices > 1): the rendezvous world is split
        # contiguously into equal slices; each task learns its slice id and
        # the DCN coordinator so libtpu's megascale transport can bridge
        # the slices. The hierarchical gradient reduce
        # (tony_tpu.parallel.overlap, MeshSpec(slices=...)) rides the DCN
        # axis this env materializes. The port is conf-fixed (same on
        # every host, like the libtpu base): every slice must know the
        # coordinator address BEFORE launch.
        slices = ctx.conf.get_int(conf_mod.JAX_SLICES, 1)
        if slices > 1:
            if n % slices:
                raise ValueError(
                    f"tony.jax.slices={slices} does not divide the "
                    f"{n}-task rendezvous world")
            per_slice = n // slices
            ms_port = ctx.conf.get_int(conf_mod.MEGASCALE_PORT, 8537)
            host0 = coordinator.rsplit(":", 1)[0]
            env[constants.ENV_MEGASCALE_COORDINATOR_ADDRESS] = \
                f"{host0}:{ms_port}"
            env[constants.ENV_MEGASCALE_NUM_SLICES] = str(slices)
            env[constants.ENV_MEGASCALE_SLICE_ID] = str(rank // per_slice)
            env[constants.ENV_MEGASCALE_PORT] = str(ms_port)
        # Comm/compute overlap (tony_tpu.parallel.overlap): inject the
        # latency-hiding-scheduler / async-collective XLA flags so
        # tony-submitted TPU jobs overlap gradient sync with backward
        # compute by default — plus the DCN set for multi-slice jobs, so
        # the per-bucket cross-slice allreduces overlap too. TPU-resourced
        # tasks only unless forced by conf: XLA aborts on flags its build
        # doesn't know, so the xla_tpu_* set would KILL a CPU-backend task
        # at import. Merged UNDER any XLA_FLAGS from tony.<jobtype>.env
        # (framework env wins the final build_task_env merge, so the merge
        # happens here, with user flag names taking precedence).
        overlap_set = ctx.conf.get(conf_mod.JAX_OVERLAP_XLA_FLAGS)
        inject = (ctx.conf.get_bool(conf_mod.JAX_OVERLAP_XLA_FLAGS)
                  if overlap_set is not None else tpus > 0)
        if inject:
            from tony_tpu.parallel.overlap import overlap_xla_flags
            user_flags = ctx.conf.task_env(ctx.job_type).get(
                constants.ENV_XLA_FLAGS, "")
            env[constants.ENV_XLA_FLAGS] = overlap_xla_flags(
                user_flags, multislice=slices > 1)
        # Checkpoint plane (tony_tpu.ckpt): ship the conf-configured
        # durable dir + cadence to the user process so train_loop's
        # save_every/restore_on_start defaults light up without script
        # changes — the script-side half of the gang-restart resume
        # contract (the executor's heartbeat reports the committed step
        # back from the same directory).
        ckpt_dir = ctx.conf.get(conf_mod.CKPT_DIR)
        if ckpt_dir:
            env[constants.ENV_CKPT_DIR] = ckpt_dir
            env[constants.ENV_CKPT_EVERY] = str(
                ctx.conf.get_int(conf_mod.CKPT_EVERY, 0))
            env[constants.ENV_CKPT_KEEP] = str(
                ctx.conf.get_int(conf_mod.CKPT_KEEP, 3))
            # Continuous publication (tony_tpu.publish): the pointer
            # cadence rides the ckpt wiring — a publication names a
            # committed step in this same directory, so the knob is
            # meaningless without tony.ckpt.dir.
            publish_every = ctx.conf.get_int(conf_mod.PUBLISH_EVERY, 0)
            if publish_every > 0:
                env[constants.ENV_PUBLISH_EVERY] = str(publish_every)
        # Shared per-gang train AOT cache (tony_tpu.ckpt.aot): every
        # worker points at one durable cache dir — the first to lower a
        # (mesh, geometry) step populates it, the rest (and post-resize
        # re-gangs) deserialize instead of re-tracing.
        train_aot = ctx.conf.get(conf_mod.TRAIN_AOT_CACHE)
        if train_aot:
            env[constants.ENV_TRAIN_AOT_CACHE] = train_aot
        # Input-data plane (tony_tpu.data): ship the stream seed so every
        # process — and every gang RESTART — builds the identical
        # deterministic example stream (Dataset's default seed). The
        # shard identity itself rides the rendezvous env above.
        data_seed = ctx.conf.get(conf_mod.DATA_SEED)
        if data_seed is not None:
            env[constants.ENV_DATA_SEED] = str(data_seed)
        # Profiler hook (SURVEY.md §5.1): tony_tpu.distributed.initialize
        # starts jax.profiler.start_server on this port in the user
        # process. The port is executor-reserved and EPHEMERAL (shipped to
        # the AM via register_callback_info) — a conf-fixed base+rank
        # collided across overlapping jobs on one host, and the trace
        # client would dial a dying predecessor's server.
        if ctx.profiler_port is not None:
            env[constants.ENV_PROFILER_PORT] = str(ctx.profiler_port)
        return env


class JAXAMAdapter(ApplicationMasterAdapter):
    def __init__(self) -> None:
        # Eager init: register_callback_info arrives on concurrent RPC
        # server threads; lazy hasattr-init could drop a rank's write.
        self.profiler_endpoints: Dict[str, str] = {}

    def receive_task_callback_info(self, task_id: str, payload: str) -> None:
        """Collect executor-pushed profiler endpoints (the SPI consumer of
        registerCallbackInfo): ``profiler_endpoints[task_id] = host:port``
        of that rank's live ``jax.profiler`` server."""
        import json

        try:
            info = json.loads(payload)
        except ValueError:
            return
        if "profiler" in info:
            self.profiler_endpoints[task_id] = str(info["profiler"])

    def validate_and_update_config(self, conf) -> None:
        # JAX jobs are SPMD gangs: parameter-server job types make no sense.
        for jt in conf.job_types():
            if jt == constants.PS and conf.instances(jt) > 0:
                raise ValueError(
                    "framework=jax is SPMD: remove tony.ps.instances "
                    "(parameters are sharded with the model, not served)")
        # Multi-slice needs equal contiguous slices of the rendezvous
        # world — fail at submit, not at gang-up on the pod.
        slices = conf.get_int(conf_mod.JAX_SLICES, 1)
        if slices < 1:
            raise ValueError(f"{conf_mod.JAX_SLICES} must be >= 1, got "
                             f"{slices}")
        if slices > 1:
            world = sum(conf.instances(jt) for jt in conf.job_types()
                        if jt not in constants.SIDECAR_JOB_TYPES)
            if world % slices:
                raise ValueError(
                    f"{conf_mod.JAX_SLICES}={slices} does not divide the "
                    f"{world}-task rendezvous world (slices must be "
                    f"equal-sized)")


class JAXFramework(Framework):
    name = "jax"

    def am_adapter(self) -> JAXAMAdapter:
        return JAXAMAdapter()

    def task_adapter(self) -> JAXTaskAdapter:
        return JAXTaskAdapter()
