"""Shared adapter base (reference: ``runtime/MLGenericRuntime.java``).

Provides the common env every runtime exports — job name, task index, the full
cluster spec, app metadata — plus per-jobtype extra env from
``tony.<jobtype>.env``.
"""

from __future__ import annotations

import json
from typing import Dict

from tony_tpu import constants
from tony_tpu.runtime import TaskContext, TaskExecutorAdapter


class MLGenericTaskAdapter(TaskExecutorAdapter):
    """Common env builder; framework adapters extend :meth:`framework_env`."""

    def build_task_env(self, ctx: TaskContext) -> Dict[str, str]:
        env: Dict[str, str] = {
            constants.ENV_JOB_TYPE: ctx.job_type,
            constants.ENV_TASK_INDEX_USER: str(ctx.index),
            constants.ENV_DIST_SPEC: json.dumps(ctx.cluster_spec, sort_keys=True),
            constants.ENV_JOB_NAME: ctx.job_type,
            constants.ENV_TASK_INDEX: str(ctx.index),
            constants.ENV_TASK_NUM: str(ctx.num_tasks()),
            constants.ENV_APP_ID: ctx.app_id,
            constants.ENV_ATTEMPT_ID: str(ctx.attempt_id),
            constants.ENV_AM_ADDRESS: ctx.am_address,
        }
        if ctx.tb_port is not None:
            env[constants.ENV_TB_PORT] = str(ctx.tb_port)
        env.update(ctx.conf.task_env(ctx.job_type))
        env.update(self.framework_env(ctx))
        return env

    def framework_env(self, ctx: TaskContext) -> Dict[str, str]:
        return {}
