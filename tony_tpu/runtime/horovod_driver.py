"""In-AM rendezvous driver for the Horovod-semantics runtime.

The reference forks a Python Gloo ``RendezvousServer`` and line-parses its
stdout for the port and slot assignments (``runtime/horovod/HorovodDriver.java``
— SURVEY.md §3.4 calls it the most intricate runtime). Because our data plane
is XLA-over-ICI rather than Gloo/NCCL, the driver here is a small in-process
TCP server that serves the computed slot table as one JSON document per
connection — same contract (workers can fetch global/local/cross ranks from a
rendezvous address), no subprocess, no stdout parsing.
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Dict, List, Optional


def compute_slots(hosts: List[str]) -> List[Dict[str, int]]:
    """Horovod slot assignment from the ordered per-rank host list:
    ``rank`` = position, ``local_rank`` = index among same-host ranks,
    ``cross_rank`` = index of this host among distinct hosts (host-major),
    sizes to match."""
    distinct: List[str] = []
    for h in hosts:
        if h not in distinct:
            distinct.append(h)
    local_counts: Dict[str, int] = {}
    slots = []
    for rank, host in enumerate(hosts):
        local_rank = local_counts.get(host, 0)
        local_counts[host] = local_rank + 1
        slots.append({
            "rank": rank,
            "size": len(hosts),
            "local_rank": local_rank,
            "cross_rank": distinct.index(host),
            "cross_size": len(distinct),
        })
    for s, host in zip(slots, hosts):
        s["local_size"] = local_counts[host]
    return slots


class HorovodDriver:
    """Serves the slot table as JSON to any connecting client."""

    def __init__(self, host: str = "127.0.0.1"):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(16)
        self.host = host
        self.port = self._sock.getsockname()[1]
        self._slots: Optional[List[Dict[str, int]]] = None
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name="horovod-driver", daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def set_hosts(self, hosts: List[str]) -> None:
        with self._lock:
            self._slots = compute_slots(hosts)

    def slots(self) -> Optional[List[Dict[str, int]]]:
        with self._lock:
            return list(self._slots) if self._slots is not None else None

    def _serve(self) -> None:
        try:
            self._sock.settimeout(0.2)
        except OSError:          # stop() closed the socket before we started
            return
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                with self._lock:
                    payload = {"ready": self._slots is not None,
                               "slots": self._slots or []}
                conn.sendall(json.dumps(payload).encode())
            except OSError:
                pass
            finally:
                conn.close()

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=2)


def fetch_slots(address: str, timeout: float = 5.0) -> Dict[str, object]:
    """Client side: fetch the slot table from a running driver."""
    host, _, port = address.rpartition(":")
    with socket.create_connection((host, int(port)), timeout=timeout) as s:
        chunks = []
        while True:
            b = s.recv(65536)
            if not b:
                break
            chunks.append(b)
    return json.loads(b"".join(chunks).decode())
