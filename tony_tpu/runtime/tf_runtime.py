"""TFRuntime: builds the ``TF_CONFIG`` JSON that drives ``tf.distribute``
ParameterServerStrategy / MultiWorkerMirroredStrategy (reference:
``runtime/TFRuntime.java`` — ``constructClusterSpec``/``buildTaskEnv``).

``TF_CONFIG`` shape::

    {"cluster": {"ps": [...], "worker": [...], "chief": [...]},
     "task": {"type": "<job_type>", "index": <i>}}

The cluster section contains only the ML job types (tensorboard/notebook and
other sidecar types are excluded, as in the reference).
"""

from __future__ import annotations

import json
from typing import Dict

from tony_tpu import constants
from tony_tpu.runtime import Framework, TaskContext
from tony_tpu.runtime.base import MLGenericTaskAdapter

# Sidecar types never included in the TF cluster spec.
_NON_CLUSTER_TYPES = set(constants.SIDECAR_JOB_TYPES)


class TFTaskAdapter(MLGenericTaskAdapter):
    def framework_env(self, ctx: TaskContext) -> Dict[str, str]:
        cluster = {jt: members for jt, members in ctx.cluster_spec.items()
                   if jt not in _NON_CLUSTER_TYPES and members}
        tf_config = {
            "cluster": cluster,
            "task": {"type": ctx.job_type, "index": ctx.index},
        }
        return {constants.ENV_TF_CONFIG: json.dumps(tf_config, sort_keys=True)}


class TFFramework(Framework):
    name = "tensorflow"

    def task_adapter(self) -> TFTaskAdapter:
        return TFTaskAdapter()
