"""StandaloneRuntime: no rendezvous env — single-task or embarrassingly
parallel jobs, and the notebook path (reference:
``runtime/StandaloneRuntime.java``)."""

from __future__ import annotations

from tony_tpu.runtime import Framework
from tony_tpu.runtime.base import MLGenericTaskAdapter


class StandaloneTaskAdapter(MLGenericTaskAdapter):
    pass  # common env only


class StandaloneFramework(Framework):
    name = "standalone"

    def task_adapter(self) -> StandaloneTaskAdapter:
        return StandaloneTaskAdapter()
