"""MXNetRuntime: DMLC kvstore parameter-server env (reference:
``runtime/MXNetRuntime.java``).

MXNet jobs use job types ``scheduler`` (1), ``server`` (N), ``worker`` (M);
every task gets the scheduler's root URI/port plus its own DMLC role.
"""

from __future__ import annotations

from typing import Dict

from tony_tpu import constants
from tony_tpu.runtime import Framework, TaskContext
from tony_tpu.runtime.base import MLGenericTaskAdapter

_ROLE_MAP = {constants.SCHEDULER: "scheduler", "server": "server",
             constants.PS: "server", constants.WORKER: "worker"}


class MXNetTaskAdapter(MLGenericTaskAdapter):
    def framework_env(self, ctx: TaskContext) -> Dict[str, str]:
        if ctx.is_sidecar():
            # Sidecars take no DMLC role (a tensorboard task must not come up
            # as a phantom worker in the kvstore ring).
            return {}
        sched = ctx.spec_of(constants.SCHEDULER, 0)
        host, _, port = sched.rpartition(":")
        n_server = sum(len(ctx.cluster_spec.get(jt, []))
                       for jt in ("server", constants.PS))
        n_worker = len(ctx.cluster_spec.get(constants.WORKER, []))
        return {
            constants.ENV_DMLC_PS_ROOT_URI: host,
            constants.ENV_DMLC_PS_ROOT_PORT: port,
            constants.ENV_DMLC_ROLE: _ROLE_MAP.get(ctx.job_type, "worker"),
            constants.ENV_DMLC_NUM_SERVER: str(n_server),
            constants.ENV_DMLC_NUM_WORKER: str(n_worker),
        }

    def validate(self, ctx: TaskContext) -> None:
        if constants.SCHEDULER not in ctx.cluster_spec:
            raise ValueError("mxnet jobs require tony.scheduler.instances=1")


class MXNetFramework(Framework):
    name = "mxnet"

    def task_adapter(self) -> MXNetTaskAdapter:
        return MXNetTaskAdapter()
