"""PyTorchRuntime: c10d TCP-store rendezvous env for ``torch.distributed``
DDP (reference: ``runtime/PyTorchRuntime.java`` — ``buildTaskEnv``).

Exports ``MASTER_ADDR``/``MASTER_PORT`` (the global-rank-0 task's registered
host/port), ``RANK``, ``WORLD_SIZE``, ``LOCAL_RANK`` and ``INIT_METHOD`` so the
user script's ``torch.distributed.init_process_group('gloo'|'nccl')`` — or,
TPU-natively, ``torch_xla``'s xrt rendezvous — comes up with no code changes.
"""

from __future__ import annotations

from typing import Dict

from tony_tpu import constants
from tony_tpu.runtime import Framework, TaskContext
from tony_tpu.runtime.base import MLGenericTaskAdapter


class PyTorchTaskAdapter(MLGenericTaskAdapter):
    def framework_env(self, ctx: TaskContext) -> Dict[str, str]:
        if ctx.is_sidecar():
            # Sidecars never join the process group: no RANK/WORLD_SIZE, or
            # init_process_group would wait on a process that never arrives.
            return {}
        master = ctx.rank0_spec()
        host, _, port = master.rpartition(":")
        local_rank, _local_size = ctx.local_rank()
        return {
            constants.ENV_MASTER_ADDR: host,
            constants.ENV_MASTER_PORT: port,
            constants.ENV_RANK: str(ctx.global_rank()),
            constants.ENV_WORLD_SIZE: str(ctx.num_cluster_tasks()),
            constants.ENV_LOCAL_RANK: str(local_rank),
            constants.ENV_INIT_METHOD: f"tcp://{master}",
        }


class PyTorchFramework(Framework):
    name = "pytorch"

    def task_adapter(self) -> PyTorchTaskAdapter:
        return PyTorchTaskAdapter()
