"""Framework-runtime SPI: the extension point the whole framework pivots on.

Mirrors ``com.linkedin.tony.Framework`` (nested ``ApplicationMasterAdapter`` /
``TaskExecutorAdapter``) + ``FrameworkType`` (upstream ``tony-core/src/main/
java/com/linkedin/tony/Framework.java``, unverified — SURVEY.md §0).

Each supported ML framework contributes two adapters:

* an **AM-side adapter** — config validation, start gating (e.g. the Horovod
  rendezvous driver must be up before workers may launch), task callbacks;
* an **executor-side adapter** — builds the rendezvous env for the user
  process (``TF_CONFIG``, ``MASTER_ADDR``…, ``HOROVOD_*``, ``DMLC_*``, or the
  JAX coordinator triple) from the assembled cluster spec.

The first-class citizen here is :class:`~tony_tpu.runtime.jax_runtime.JAXRuntime`
(the BASELINE.json north star): rendezvous is ``jax.distributed.initialize
(coordinator_address, num_processes, process_id)`` and the data plane is XLA
collectives over ICI/DCN — no NCCL anywhere.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, TYPE_CHECKING

from tony_tpu import constants

if TYPE_CHECKING:  # pragma: no cover
    from tony_tpu.conf import TonyConfig
    from tony_tpu.session import TonySession


@dataclass
class TaskContext:
    """Everything an executor-side adapter may need to build the user env
    (reference: the executor fields passed into ``buildTaskEnv``)."""
    conf: "TonyConfig"
    job_type: str
    index: int
    cluster_spec: Dict[str, List[str]]      # {job_type: ["host:port", ...]}
    am_address: str
    app_id: str
    attempt_id: int = 1
    tb_port: Optional[int] = None
    profiler_port: Optional[int] = None     # executor-reserved, ephemeral
    callback_info: Dict[str, str] = field(default_factory=dict)  # AM-pushed extras

    # -- derived helpers shared by adapters --------------------------------
    def job_types(self) -> List[str]:
        return self.conf.job_types()

    def ml_job_types(self) -> List[str]:
        """Job types that are part of the rendezvous world: everything except
        sidecars (tensorboard/notebook/driver). Rank assignment, world size
        and coordinator selection all run over these only — a configured
        sidecar must never become the coordinator or inflate WORLD_SIZE."""
        return [jt for jt in self.job_types()
                if jt not in constants.SIDECAR_JOB_TYPES]

    def is_sidecar(self) -> bool:
        return self.job_type in constants.SIDECAR_JOB_TYPES

    def num_tasks(self) -> int:
        """All tasks in the job, sidecars included (``TONY_NUM_TASKS``)."""
        return sum(len(v) for v in self.cluster_spec.values())

    def num_cluster_tasks(self) -> int:
        """World size for rendezvous purposes: sidecars excluded."""
        return sum(len(self.cluster_spec.get(jt, []))
                   for jt in self.ml_job_types())

    def global_rank(self) -> int:
        """Dense rank over (ml_job_types order, index) — must match
        ``TonySession.global_rank``. Raises for sidecar tasks and for
        out-of-range indices (mirroring ``TonySession.global_rank``)."""
        rank = 0
        for jt in self.ml_job_types():
            n = len(self.cluster_spec.get(jt, []))
            if jt == self.job_type:
                if not (0 <= self.index < n):
                    raise KeyError(f"unknown task {self.job_type}:{self.index}")
                return rank + self.index
            rank += n
        raise KeyError(f"job type {self.job_type} not in the rendezvous world")

    def spec_of(self, job_type: str, index: int) -> str:
        members = self.cluster_spec.get(job_type, [])
        if index >= len(members) or not members[index]:
            raise KeyError(f"no spec for {job_type}:{index}")
        return members[index]

    def rank0_spec(self) -> str:
        """host:port of the global-rank-0 task (the coordinator) — the first
        non-sidecar job type's task 0."""
        return self.spec_of(self.ml_job_types()[0], 0)

    def host_of(self, job_type: str, index: int) -> str:
        return self.spec_of(job_type, index).rsplit(":", 1)[0]

    def my_host(self) -> str:
        return self.host_of(self.job_type, self.index)

    def host_cohort(self) -> List[tuple[int, str]]:
        """(global_rank, job_type) of every rendezvous task sharing this
        task's host, ordered by global rank — the basis for local-rank and
        chip-pinning math."""
        host = self.my_host()
        cohort = []
        rank = 0
        for jt in self.ml_job_types():
            for spec in self.cluster_spec.get(jt, []):
                if spec and spec.rsplit(":", 1)[0] == host:
                    cohort.append((rank, jt))
                rank += 1
        return cohort

    def local_rank(self) -> tuple[int, int]:
        """(local_rank, local_size) among rendezvous tasks sharing this task's
        host, ordered by global rank — Horovod/PyTorch local-rank semantics."""
        me = self.global_rank()
        cohort = [r for r, _jt in self.host_cohort()]
        return cohort.index(me), len(cohort)


class TaskExecutorAdapter:
    """Executor-side SPI (reference: ``Framework.TaskExecutorAdapter``)."""

    def need_reserve_tb_port(self, ctx: TaskContext) -> bool:
        """Whether this task should reserve a sidecar HTTP port: a dedicated
        ``tensorboard`` or ``notebook`` task, or the chief when no dedicated
        tensorboard task exists."""
        return ctx.job_type in (constants.TENSORBOARD, constants.NOTEBOOK) or (
            ctx.job_type in constants.CHIEF_LIKE_JOB_TYPES and
            constants.TENSORBOARD not in ctx.job_types())

    def need_reserve_profiler_port(self, ctx: TaskContext) -> bool:
        """Whether the executor should reserve an ephemeral profiler port
        for this task. Ephemeral, not conf-fixed: a fixed port-base
        collides whenever two jobs (or a dying predecessor's user process)
        share a host — the trace client then dials the wrong server."""
        return False

    def build_task_env(self, ctx: TaskContext) -> Dict[str, str]:
        """Rendezvous env for the user process. Subclasses extend."""
        raise NotImplementedError

    def validate(self, ctx: TaskContext) -> None:
        """Pre-launch sanity hook (default: none)."""


class ApplicationMasterAdapter:
    """AM-side SPI (reference: ``Framework.ApplicationMasterAdapter``)."""

    def set_session(self, session: "TonySession") -> None:
        self.session = session

    def validate_and_update_config(self, conf: "TonyConfig") -> None:
        """Framework-specific config validation/defaulting (AM start)."""

    def can_start_task(self, job_type: str, index: int) -> bool:
        """Gate container launches (e.g. Horovod: driver must be ready)."""
        return True

    def on_all_registered(self) -> None:
        """Called once when the gang barrier passes — adapters that need a
        global view (Horovod slot assignment) compute it here."""

    def callback_info(self) -> Dict[str, str]:
        """Extra key/values shipped to every executor with the cluster spec
        (e.g. the Horovod rendezvous address)."""
        return {}

    def receive_task_callback_info(self, task_id: str, payload: str) -> None:
        """Executor-pushed framework-specific info (reference RPC of the
        same name)."""

    def stop(self) -> None:
        """Tear down AM-side resources (rendezvous drivers etc.)."""


class Framework:
    """One supported framework: a name plus its two adapter factories."""

    name: str = "abstract"

    def am_adapter(self) -> ApplicationMasterAdapter:
        return ApplicationMasterAdapter()

    def task_adapter(self) -> TaskExecutorAdapter:
        raise NotImplementedError


def _registry() -> Dict[str, Framework]:
    from tony_tpu.runtime.jax_runtime import JAXFramework
    from tony_tpu.runtime.tf_runtime import TFFramework
    from tony_tpu.runtime.pytorch_runtime import PyTorchFramework
    from tony_tpu.runtime.horovod_runtime import HorovodFramework
    from tony_tpu.runtime.mxnet_runtime import MXNetFramework
    from tony_tpu.runtime.standalone import StandaloneFramework
    fws = [JAXFramework(), TFFramework(), PyTorchFramework(),
           HorovodFramework(), MXNetFramework(), StandaloneFramework()]
    return {f.name: f for f in fws}


FRAMEWORKS: Dict[str, "Framework"] = {}


def get_framework(name: str) -> Framework:
    """Look up a framework by ``tony.application.framework`` value
    (reference: ``Framework.of(FrameworkType)``)."""
    if not FRAMEWORKS:
        FRAMEWORKS.update(_registry())
    try:
        return FRAMEWORKS[name]
    except KeyError:
        raise ValueError(f"unknown framework {name!r}; known: {sorted(FRAMEWORKS)}")


# Populate eagerly so `name in FRAMEWORKS` works for conf.validate().
FRAMEWORKS.update(_registry())
