"""TonyClient: the gateway-side submitter + monitor (layer L5).

Mirrors ``com.linkedin.tony.TonyClient`` (upstream ``tony-core/src/main/java/
com/linkedin/tony/TonyClient.java`` ≈1,200 LoC, unverified — SURVEY.md §0,
call stack §3.1). Responsibilities carried over:

* assemble the effective config (file + ``-D`` overrides + CLI switches) and
  sanity-check it before submission (reference: ``TonyClient#init``);
* stage the user's ``--src_dir`` into the job directory — the moral
  equivalent of the HDFS staging upload (``Utils.uploadFileAndSetConfResources``,
  SURVEY.md §2.1 "Resource localization"); executors then localize a
  per-container copy;
* "submit the application": here the AM launches as a local subprocess
  (``python -m tony_tpu.am``) instead of a YARN AM container — the
  :mod:`tony_tpu.scheduler` substrate behind the AM decides where executors
  actually run (local processes or TPU-VM hosts over SSH);
* the 1-second monitor poll loop: ``get_task_infos`` + ``get_job_status``
  over the control-plane RPC, printing task transitions and the TensorBoard
  URL exactly like the reference's ``monitorApplication``;
* listener callbacks for task-info updates (reference: ``addListener``);
* the exit-code contract: 0 iff the job's final status is SUCCEEDED.
"""

from __future__ import annotations

import itertools
import json
import os
import shutil
import subprocess
import sys
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional

from tony_tpu import conf as conf_mod
from tony_tpu import constants
from tony_tpu.am import AM_ADDRESS_FILE, AM_TOKEN_FILE, FINAL_STATUS_FILE
from tony_tpu.conf import TonyConfig
from tony_tpu.rpc import RpcClient
from tony_tpu.util import child_pythonpath, default_workdir

_POLL_INTERVAL_S = 0.2


_app_seq = itertools.count(1)


def new_app_id() -> str:
    """``app_<epoch_ms>_<pid><seq>`` — YARN-shaped, collision-free across
    processes (ms + pid) and within one process (sequence counter)."""
    return (f"app_{int(time.time() * 1000)}_"
            f"{os.getpid() % 10000:04d}{next(_app_seq):03d}")


class TonyClient:
    """One submission lifecycle: :meth:`run` returns the job exit code."""

    def __init__(self, conf: TonyConfig,
                 src_dir: Optional[str | Path] = None,
                 workdir: Optional[str | Path] = None,
                 app_id: Optional[str] = None,
                 am_host: str = "127.0.0.1",
                 quiet: bool = False,
                 stream: Optional[object] = None):
        self.conf = conf
        self.src_dir = Path(src_dir) if src_dir else None
        self.workdir = Path(workdir) if workdir else default_workdir()
        self.app_id = app_id or new_app_id()
        self.am_host = am_host
        self.quiet = quiet
        self.stream = stream or sys.stderr
        # Resolved: paths derived from the job dir (staged venv/src) are
        # shipped through the conf to executors running with a DIFFERENT
        # cwd — a relative --workdir must not produce relative staged
        # paths (found live: a relative venv path resolved fine in the
        # AM's cwd, then silently vanished in every container).
        self.job_dir = (self.workdir / self.app_id).resolve()
        self.am_proc: Optional[subprocess.Popen] = None
        self._am_launches = 0
        self.final_status: Optional[str] = None
        self.final_message = ""
        self.tensorboard_url: Optional[str] = None
        self.submit_time: Optional[float] = None
        self.all_running_latency_s: Optional[float] = None
        self._listeners: List[Callable[[List[Dict]], None]] = []
        self._last_status: Dict[str, str] = {}

    # -- reference: TonyClient#addListener ---------------------------------
    def add_listener(self, fn: Callable[[List[Dict]], None]) -> None:
        """``fn(task_infos)`` invoked on every monitor poll."""
        self._listeners.append(fn)

    def _log(self, msg: str) -> None:
        if not self.quiet:
            print(msg, file=self.stream, flush=True)

    def _notify(self, infos: List[Dict]) -> None:
        """Listener fan-out. Guarded: a broken listener must not abort the
        monitor loop (which would SIGKILL a healthy AM in the finally path)."""
        for fn in self._listeners:
            try:
                fn(infos)
            except Exception as e:  # noqa: BLE001 — listener is user code
                self._log(f"listener {fn!r} raised: {e}")

    # -- staging (reference: HDFS upload in TonyClient#run) ----------------
    def stage(self) -> None:
        self.job_dir.mkdir(parents=True, exist_ok=True)
        if self.src_dir is not None:
            if not self.src_dir.is_dir():
                raise FileNotFoundError(f"--src_dir {self.src_dir} not found")
            dest = self.job_dir / "src"
            if not dest.exists():
                # The workdir may live INSIDE src_dir (e.g. `tony submit
                # --src_dir . --workdir ./jobs`): copying it would recurse
                # into the copy being made until ENAMETOOLONG. Prune any
                # entry that is (or contains) the job workdir.
                job_root = self.job_dir.resolve()
                skip = {job_root, job_root.parent}  # job dir AND workdir:
                # --workdir . makes workdir_root == src_dir (never a child
                # entry), but the job dir itself then is one.

                def _skip_workdir(path, names):
                    p = Path(path)
                    return [n for n in names if (p / n).resolve() in skip]

                shutil.copytree(self.src_dir, dest, ignore=_skip_workdir)
        # Stage the venv (dir or archive) next to the job, like the
        # reference's HDFS venv upload; executors localize per container.
        venv = self.conf.get(conf_mod.PYTHON_VENV)
        if venv:
            src = Path(venv)
            if src.is_dir():
                staged = self.job_dir / "venv"
                if not staged.exists():
                    shutil.copytree(src, staged, symlinks=True)
            elif src.is_file():
                staged = self.job_dir / src.name
                if not staged.exists():
                    shutil.copy2(src, staged)
            else:
                raise FileNotFoundError(f"--python_venv {venv} not found")
            self.conf.set(conf_mod.PYTHON_VENV, str(staged))
        # tony.containers.resources: stage each entry under <job>/resources
        # and rewrite the conf to the staged copies — executors resolve
        # entries by basename against the (possibly remote) resources dir.
        entries = self.conf.get_list(conf_mod.CONTAINERS_RESOURCES)
        if entries:
            res_dir = self.job_dir / "resources"
            res_dir.mkdir(exist_ok=True)
            names = [Path(e.partition("#")[0]).name for e in entries]
            dupes = {n for n in names if names.count(n) > 1}
            if dupes:
                # Entries localize by basename into one flat dir; a
                # collision would silently ship the first entry's bytes
                # under the second entry's name.
                raise ValueError(
                    f"{conf_mod.CONTAINERS_RESOURCES}: duplicate "
                    f"basenames {sorted(dupes)}")
            staged_csv = []
            for entry in entries:
                path_s, marker, flag = entry.partition("#")
                src = Path(path_s)
                if not src.exists():
                    raise FileNotFoundError(
                        f"{conf_mod.CONTAINERS_RESOURCES} entry "
                        f"{path_s!r} not found")
                dest = res_dir / src.name
                if not dest.exists():
                    if src.is_dir():
                        shutil.copytree(src, dest, symlinks=True)
                    else:
                        shutil.copy2(src, dest)
                staged_csv.append(f"{dest}{marker}{flag}")
            self.conf.set(conf_mod.CONTAINERS_RESOURCES,
                          ",".join(staged_csv))
        self.conf.save(self.job_dir / "client-conf.json")

    def submit(self) -> None:
        """Validate, stage, and launch the AM process (reference:
        ``createYarnApplication`` + ``submitApplication``)."""
        self.conf.validate()
        self.stage()
        if self.conf.get_bool(conf_mod.SECURITY_ENABLED, False):
            # Acquire-at-submit (reference: delegation tokens fetched by
            # TonyClient before the AM context is built); the AM and its
            # executors inherit these, they never re-acquire.
            from tony_tpu import security
            provider = security.provider_for(self.conf)
            self._credentials = provider.acquire(self.conf, self.job_dir)
            security.write_credentials(self.job_dir, self._credentials)
        self._launch_am()
        self._log(f"submitted application {self.app_id} "
                  f"(job dir {self.job_dir})")

    def _launch_am(self) -> None:
        am_log = open(self.job_dir / "am.log", "ab")
        env = dict(os.environ)
        env["PYTHONPATH"] = child_pythonpath(env)
        from tony_tpu.util import control_plane_site_env
        env.update(control_plane_site_env())
        # Submit timestamp for the AM's submit→all-RUNNING latency metric.
        self.submit_time = time.time()
        env[constants.ENV_SUBMIT_TS] = repr(self.submit_time)
        self.am_proc = subprocess.Popen(
            # -S: the AM is stdlib-only; skipping the site import (the ML
            # stack's sitecustomize costs ~1.8 s) is pure submit→running
            # latency. Lazy imports still work via TONY_SITE_DIRS
            # (control_plane_site_env above + restore_site_dirs in the AM
            # __main__) — NOT via PYTHONPATH, which reaches user processes.
            [sys.executable, "-S", "-m", "tony_tpu.am",
             "--conf", str(self.job_dir / "client-conf.json"),
             "--app-id", self.app_id,
             "--job-dir", str(self.job_dir),
             "--host", self.am_host],
            env=env, stdout=am_log, stderr=subprocess.STDOUT,
            start_new_session=True)
        am_log.close()
        self._am_launches += 1

    # -- monitoring (reference: monitorApplication poll loop) --------------
    def _am_address(self) -> Optional[str]:
        path = self.job_dir / AM_ADDRESS_FILE
        if path.is_file():
            addr = path.read_text().strip()
            if addr:
                return addr
        return None

    def _token(self) -> Optional[str]:
        creds = getattr(self, "_credentials", None)
        if creds is not None:
            return creds.get("token")
        from tony_tpu import security
        creds = security.read_credentials(self.job_dir)
        if creds is not None:
            return creds.get("token")
        # Pre-SPI jobs (an already-running AM from an older client).
        path = self.job_dir / AM_TOKEN_FILE
        return path.read_text().strip() if path.is_file() else None

    def _print_transitions(self, infos: List[Dict]) -> None:
        for info in infos:
            tid = f"{info['job_type']}:{info['index']}"
            # The poll is sampled, so a fast worker can pass through
            # RUNNING between two polls — walk the AM's status history
            # (to_info carries it) and print every transition not yet
            # logged, in order, instead of only the latest snapshot.
            # Older AMs (no history) degrade to the snapshot alone.
            history = info.get("status_history") or [info["status"]]
            statuses = [s for s in history if s != "NEW"]
            printed = self._last_status.get(tid, [])
            if statuses[:len(printed)] != printed:
                printed = []          # a new AM attempt restarted the task
            for status in statuses[len(printed):]:
                where = f" on {info['host']}" if info.get("host") else ""
                extra = ""
                if status in ("FAILED", "LOST") and info.get("diagnostics"):
                    extra = f" — {info['diagnostics']}"
                self._log(f"task {tid} -> {status}{where}{extra}")
            self._last_status[tid] = statuses

    def monitor(self, timeout: Optional[float] = None) -> int:
        """Poll until the job reaches a final status; returns the exit code
        (0 iff SUCCEEDED). Ctrl-C kills the job via ``finish_application``."""
        assert self.am_proc is not None, "call submit() first"
        deadline = time.monotonic() + timeout if timeout else None
        client: Optional[RpcClient] = None
        try:
            while True:
                final = self._read_final_status()
                if final is not None:
                    # Drain: the AM has written its verdict; report it, plus
                    # the terminal task transitions the live poll may have
                    # missed in the AM's last tick.
                    self.final_status = final["status"]
                    self.final_message = final.get("message", "")
                    infos = final.get("task_infos") or []
                    if infos:
                        self._print_transitions(infos)
                        self._notify(infos)
                    break
                if self.am_proc.poll() is not None \
                        and self._read_final_status() is None:
                    # AM process died without a verdict. Reference: the RM
                    # relaunches the AM container up to yarn's am
                    # max-attempts and the new attempt re-runs the session
                    # (executors of the dead attempt self-terminate on
                    # heartbeat loss). Same contract here via
                    # tony.am.max-attempts.
                    max_attempts = self.conf.get_int(
                        conf_mod.AM_MAX_ATTEMPTS, 1)
                    if self._am_launches < max_attempts:
                        self._log(
                            f"AM process exited with "
                            f"{self.am_proc.returncode} before a final "
                            f"status; relaunching "
                            f"(attempt {self._am_launches + 1}"
                            f"/{max_attempts})")
                        (self.job_dir / AM_ADDRESS_FILE).unlink(
                            missing_ok=True)
                        if client is not None:
                            client.close()
                            client = None
                        # Let the dead attempt's executors notice the AM
                        # loss and release their resources (chips!) before
                        # the new attempt spawns its gang — otherwise the
                        # two attempts double-book the hardware.
                        hb_s = self.conf.get_int(
                            conf_mod.TASK_HEARTBEAT_INTERVAL_MS, 1000) / 1e3
                        misses = max(3, self.conf.get_int(
                            conf_mod.TASK_MAX_MISSED_HEARTBEATS, 25))
                        # Worst-case executor detection time — NOT capped
                        # below it: relaunching early double-books chips
                        # against the dead attempt's still-live executors.
                        # Each missed heartbeat costs up to the RPC client's
                        # worst-case call time (retry window + a last
                        # attempt's socket connect+recv — an unreachable
                        # host blackholes, it doesn't refuse) plus the
                        # inter-beat wait.
                        per_call = RpcClient.worst_case_call_s(
                            max(1.0, hb_s))
                        grace = misses * (per_call + hb_s) + 2.0
                        self._log(f"waiting {grace:.0f}s for the previous "
                                  f"attempt's executors to wind down")
                        time.sleep(grace)
                        self._last_status.clear()  # re-log attempt-2 states
                        self._launch_am()
                        continue
                    self.final_status = "FAILED"
                    self.final_message = (
                        f"AM process exited with {self.am_proc.returncode} "
                        f"before reporting a final status (see "
                        f"{self.job_dir / 'am.log'})")
                    break
                addr = self._am_address()
                if addr is not None:
                    if client is None:
                        client = RpcClient(addr, token=self._token(),
                                           timeout=2.0)
                    try:
                        infos = client.call("get_task_infos")
                        status = client.call("get_job_status")
                    except Exception:
                        infos, status = None, None  # AM mid-shutdown; re-poll
                    if infos is not None:
                        self._print_transitions(infos)
                        self._notify(infos)
                    if status is not None:
                        url = status.get("tensorboard_url")
                        if url and url != self.tensorboard_url:
                            self.tensorboard_url = url
                            self._log(f"TensorBoard at {url}")
                        lat = status.get("all_running_latency_s")
                        if lat and self.all_running_latency_s is None:
                            self.all_running_latency_s = float(lat)
                            self._log(f"all tasks running {lat:.2f}s "
                                      f"after submit")
                if deadline and time.monotonic() > deadline:
                    self._log(f"client monitor timed out; killing {self.app_id}")
                    self.kill("client monitor timeout")
                    self.final_status = "KILLED"
                    self.final_message = "client monitor timeout"
                    break
                time.sleep(_POLL_INTERVAL_S)
        except KeyboardInterrupt:
            self._log(f"interrupt: killing application {self.app_id}")
            self.kill("killed by client interrupt")
            self.final_status = "KILLED"
            self.final_message = "killed by client interrupt"
        finally:
            if client is not None:
                client.close()
            self._reap_am()
        self._log(f"application {self.app_id} finished: {self.final_status}"
                  + (f" — {self.final_message}" if self.final_message else ""))
        return (constants.EXIT_SUCCESS if self.final_status == "SUCCEEDED"
                else constants.EXIT_FAILURE)

    def _read_final_status(self) -> Optional[Dict]:
        path = self.job_dir / FINAL_STATUS_FILE
        if not path.is_file():
            return None
        try:
            return json.loads(path.read_text())
        except (ValueError, OSError):
            return None

    def _reap_am(self, grace_s: float = 10.0) -> None:
        if self.am_proc is None:
            return
        try:
            self.am_proc.wait(timeout=grace_s)
        except subprocess.TimeoutExpired:
            self.am_proc.kill()
            self.am_proc.wait()

    def kill(self, reason: str = "killed by client") -> None:
        """Best-effort job kill over RPC, then SIGTERM the AM."""
        addr = self._am_address()
        if addr is not None:
            try:
                with RpcClient(addr, token=self._token(), timeout=2.0) as c:
                    c.call("finish_application", reason=reason)
                    return
            except Exception:
                pass
        if self.am_proc is not None and self.am_proc.poll() is None:
            self.am_proc.terminate()

    def run(self, timeout: Optional[float] = None) -> int:
        """submit + monitor: the whole reference ``TonyClient.run`` path."""
        self.submit()
        return self.monitor(timeout=timeout)
