"""Control-plane RPC: the AM↔executor (and client↔AM) wire.

Mirrors the role of ``com.linkedin.tony.rpc`` (upstream ``tony-core/src/main/
java/com/linkedin/tony/rpc/`` — ``ApplicationRpc``/``ApplicationRpcServer``/
``ApplicationRpcClient`` + ``MetricsRpc``, unverified, SURVEY.md §0). The
reference uses Hadoop RPC over protobuf; the verbs are what matter
(SURVEY.md §2.1 "Control-plane RPC"), not the wire, so this implementation is
newline-delimited JSON over TCP: zero codegen, stdlib-only, debuggable with
``nc``. The protocol verbs carried over:

    register_worker_spec, get_cluster_spec, taskExecutorHeartbeat→heartbeat,
    register_execution_result, get_task_infos, register_tensorboard_url,
    register_callback_info, metrics_report (MetricsRpc), get_job_status,
    finish_application

Security: when ``tony.security.enabled`` is true the client must present the
job token (shipped to executors via env — the moral equivalent of the
reference's ClientToAMToken); mismatches are rejected before dispatch.
"""

from __future__ import annotations

import json
import random
import socket
import socketserver
import threading
import time
from typing import Any, Callable, Dict, Optional

from tony_tpu import chaos

# Env var carrying the job token to executors (security.enabled only).
ENV_JOB_TOKEN = "TONY_JOB_TOKEN"


class RpcError(Exception):
    """Remote call failed: transported application-level error."""


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        server: RpcServer = self.server  # type: ignore[assignment]
        while True:
            try:
                line = self.rfile.readline()
            except OSError:
                return
            if not line:
                return
            try:
                req = json.loads(line)
                method = req["method"]
                params = req.get("params") or {}
                if server.token and req.get("token") != server.token:
                    resp = {"ok": False, "error": "invalid job token"}
                else:
                    fn = server.lookup(method)
                    result = fn(**params)
                    resp = {"ok": True, "result": result}
            except RpcError as e:
                resp = {"ok": False, "error": str(e)}
            except Exception as e:  # noqa: BLE001 — transported to caller
                resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            try:
                self.wfile.write((json.dumps(resp) + "\n").encode())
                self.wfile.flush()
            except OSError:
                return


class RpcServer:
    """Threaded JSON-lines RPC server dispatching to ``rpc_<method>``
    callables on a handler object (reference: ``ApplicationRpcServer``)."""

    def __init__(self, handler: object, host: str = "0.0.0.0",
                 port: int = 0, token: Optional[str] = None):
        self._handler = handler
        self.token = token
        self._tcp = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=False)
        self._tcp.allow_reuse_address = True
        self._tcp.daemon_threads = True
        self._tcp.server_bind()
        self._tcp.server_activate()
        self.host, self.port = self._tcp.server_address[:2]
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, name="tony-rpc", daemon=True)

    # socketserver instantiates _Handler with the TCPServer as .server; give
    # that object the lookup/token surface _Handler expects.
    def start(self) -> "RpcServer":
        self._tcp.lookup = self.lookup          # type: ignore[attr-defined]
        self._tcp.token = self.token            # type: ignore[attr-defined]
        self._thread.start()
        return self

    @property
    def address(self) -> str:
        host = self.host if self.host != "0.0.0.0" else "127.0.0.1"
        return f"{host}:{self.port}"

    def lookup(self, method: str) -> Callable[..., Any]:
        fn = getattr(self._handler, f"rpc_{method}", None)
        if fn is None or not callable(fn):
            raise RpcError(f"unknown RPC method {method!r}")
        return fn

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()
        self._thread.join(timeout=5)


class RpcClient:
    """Reconnecting JSON-lines RPC client (reference: ``ApplicationRpcClient``).

    One persistent connection, re-dialed on failure; every call retries
    transport errors up to ``timeout`` seconds with BOUNDED JITTERED
    exponential backoff (base ``retry_interval``, doubling to
    :data:`BACKOFF_CAP_S`, ×[0.5, 1.5) jitter) — executors come up before
    the AM socket is reachable in some orderings, and the reference's
    Hadoop RPC retries the same way. The jitter keeps a gang of
    executors whose AM hiccuped from re-dialing in lockstep; the cap
    keeps a long-timeout call responsive once the fault clears.
    """

    def __init__(self, address: str, token: Optional[str] = None,
                 timeout: float = 30.0, retry_interval: float = 0.2):
        host, _, port = address.rpartition(":")
        self._addr = (host, int(port))
        self.token = token
        self.timeout = timeout
        self.retry_interval = retry_interval
        self._sock: Optional[socket.socket] = None
        self._file = None
        self._lock = threading.Lock()

    # Backoff ceiling for the transport-retry loop: delays double from
    # retry_interval up to this cap, so a transient fault early in a long
    # window is probed promptly while a dead AM is not hammered.
    BACKOFF_CAP_S = 2.0

    # Per-operation socket timeout cap. Individual connect/recv calls are
    # additionally capped by the client's own retry window so that a
    # short-timeout client (the executor's heartbeat probe) fails FAST when
    # the AM host is unreachable rather than refusing — an unreachable host
    # blackholes SYNs and a bare connect would block the full 10s.
    SOCKET_TIMEOUT_S = 10.0

    @classmethod
    def _per_op(cls, timeout: float) -> float:
        """Single-op (connect/recv) cap for a call with this retry window
        — THE one definition; worst_case_call_s/_connect/call all use it."""
        return min(cls.SOCKET_TIMEOUT_S, max(0.1, timeout))

    @classmethod
    def worst_case_call_s(cls, timeout: float) -> float:
        """Upper bound on one :meth:`call`'s wall time: the retry window,
        plus one last attempt begun just before the deadline that blocks
        for a full socket connect + recv. The client's AM-relaunch grace
        is derived from this."""
        return timeout + 2.0 * cls._per_op(timeout)

    def _connect(self, per_op: Optional[float] = None) -> None:
        """(Re)dial. Caller holds ``self._lock`` (``call`` does)."""
        self._close_locked()
        if per_op is None:
            per_op = self._per_op(self.timeout)
        self._sock = socket.create_connection(self._addr, timeout=per_op)
        self._file = self._sock.makefile("rwb")

    def call(self, method: str, _timeout: Optional[float] = None,
             **params: Any) -> Any:
        """Invoke ``method`` remotely; retries transport errors until
        ``timeout`` (``_timeout`` overrides per call — deadline-driven
        loops like the executor's gang barrier must not block a full
        default window past their own deadline), raises :class:`RpcError`
        on application errors."""
        if any(k.startswith("_") for k in params):
            # "_"-prefixed kwargs are reserved for client-side options
            # (today: _timeout). Without this guard an RPC param named
            # _timeout would silently become the deadline override — and,
            # conversely, this line is where a future _retries/_trace
            # option is protected from leaking onto the wire.
            raise TypeError(
                f"reserved client-option name(s) in RPC params: "
                f"{sorted(k for k in params if k.startswith('_'))}")
        req = {"method": method, "params": params}
        if self.token:
            req["token"] = self.token
        payload = (json.dumps(req) + "\n").encode()
        effective = self.timeout if _timeout is None else _timeout
        per_op = self._per_op(effective)
        chaos.rpc_delay()
        deadline = time.monotonic() + effective
        last_err: Optional[Exception] = None
        attempt = 0
        while time.monotonic() < deadline:
            try:
                with self._lock:
                    if self._file is None:
                        self._connect(per_op)
                    elif self._sock is not None:
                        # Re-arm the per-op cap: a persistent connection
                        # keeps the timeout of the call that dialed it.
                        self._sock.settimeout(per_op)
                    assert self._file is not None
                    self._file.write(payload)
                    self._file.flush()
                    line = self._file.readline()
                if not line:
                    raise ConnectionError("server closed connection")
                resp = json.loads(line)
                if resp.get("ok"):
                    return resp.get("result")
                raise RpcError(resp.get("error", "unknown remote error"))
            except RpcError:
                raise
            except (OSError, ValueError, ConnectionError) as e:
                last_err = e
                with self._lock:
                    self._close_locked()
                delay = min(self.retry_interval * (2.0 ** attempt),
                            self.BACKOFF_CAP_S)
                delay *= 0.5 + random.random()  # jitter in [0.5x, 1.5x)
                # Never sleep past the deadline — the loop guard would
                # otherwise charge the overshoot to the caller's budget.
                delay = min(delay, max(0.0, deadline - time.monotonic()))
                attempt += 1
                if delay > 0:
                    time.sleep(delay)
        raise ConnectionError(
            f"RPC {method} to {self._addr} failed after {effective}s: "
            f"{last_err}")

    def _close_locked(self) -> None:
        """Tear down the connection. Caller holds ``self._lock``."""
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        # Under the lock: teardown (executor finally, __exit__) races a
        # sharer mid-call — the TaskMonitor thread and the executor main
        # thread share one client — and nulling _file under a writer was
        # an AttributeError crash, not a clean ConnectionError retry
        # (found by the concurrency audit; call() already serializes all
        # connection use on this lock).
        with self._lock:
            self._close_locked()

    def __enter__(self) -> "RpcClient":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()


class ApplicationRpcHandler:
    """Server-side verb set bridging RPC to a :class:`TonySession` — the
    reference's ``ApplicationRpc`` service implementation living inside the
    AM (``TonyApplicationMaster`` implements these verbs against its session).

    The AM subclasses/owns this and may hook extra behavior (events, adapter
    callbacks) via the ``on_*`` callback slots.
    """

    def __init__(self, session):
        self.session = session
        self.callback_info: Dict[str, str] = {}
        self.on_registered: Optional[Callable[[str, int], None]] = None
        self.on_result: Optional[Callable[[str, int, int, str], None]] = None
        self.on_all_registered: Optional[Callable[[], None]] = None
        self.on_metrics: Optional[Callable[[str, int, Dict[str, float]],
                                           None]] = None
        self.on_callback_info: Optional[Callable[[str, str], None]] = None
        # Armed by the AM only when tony.resize.enabled — an unset slot
        # makes the ``tony resize`` verb a clean application error.
        self.on_resize: Optional[Callable[[int], None]] = None
        self._all_registered_fired = False
        self._fire_lock = threading.Lock()

    def reset(self, session) -> None:
        """Point the handler at a fresh session (AM gang restart: the RPC
        server survives across attempts, the session does not)."""
        with self._fire_lock:
            self.session = session
            self.callback_info = {}
            self._all_registered_fired = False

    # -- executor-facing verbs --------------------------------------------
    def rpc_register_worker_spec(self, job_type: str, index: int,
                                 host: str, port: int) -> Dict[str, Any]:
        self.session.on_registered(job_type, index, host, port)
        if self.on_registered:
            self.on_registered(job_type, index)
        if self.session.all_registered():
            # The once-only adapter callback runs under the lock BEFORE the
            # barrier becomes visible to get_cluster_spec, so no executor can
            # observe a complete spec with missing callback_info. A second
            # pass (executor relaunch after preemption) re-marks RUNNING but
            # does not re-fire the adapter.
            with self._fire_lock:
                if not self._all_registered_fired:
                    if self.on_all_registered:
                        self.on_all_registered()
                    self._all_registered_fired = True
            self.session.on_running()
        return {"task_id": f"{job_type}:{index}"}

    def rpc_get_cluster_spec(self) -> Dict[str, Any]:
        complete = self._all_registered_fired and self.session.all_registered()
        return {
            "complete": complete,
            "spec": self.session.cluster_spec() if complete else {},
            "callback_info": dict(self.callback_info),
        }

    def rpc_heartbeat(self, job_type: str, index: int,
                      ckpt_step: Optional[int] = None,
                      serve: Optional[Dict[str, float]] = None) -> Any:
        """Liveness + checkpoint progress + serving telemetry: executors
        that see a ``tony.ckpt.dir`` piggyback the last COMMITTED step;
        serve-replica executors piggyback the engine's published
        qps/p99_ms/queue_depth (the autoscaler's signal). Both params
        optional — seed-era executors send neither.

        Returns bare ``True`` normally; when an elastic resize has the
        gang draining, returns ``{"ok": True, "drain": True}`` so the
        executor can relay the drain directive to its user process (the
        asymmetry keeps seed-era executors, which only truth-test the
        reply, working unchanged)."""
        self.session.on_heartbeat(job_type, index, ckpt_step=ckpt_step,
                                  serve=serve)
        if self.session.drain_pending(job_type, index):
            return {"ok": True, "drain": True}
        return True

    def rpc_resize(self, num_workers: int) -> bool:
        """Operator-triggered elastic resize (``tony resize N``): ask the
        AM to drain, commit, and re-gang at ``num_workers``. Validation of
        the target count is the AM's job (it knows min-workers and whether
        a resize is already in flight); here we only reject garbage and
        require the AM to have opted in via the callback slot."""
        n = int(num_workers)
        if n < 1:
            raise ValueError(f"resize target must be >= 1, got {n}")
        if self.on_resize is None:
            raise RuntimeError(
                "resize is not enabled for this application "
                "(tony.resize.enabled=false)")
        self.on_resize(n)
        return True

    def rpc_register_execution_result(self, job_type: str, index: int,
                                      exit_code: int,
                                      diagnostics: str = "") -> bool:
        self.session.on_task_result(job_type, index, exit_code, diagnostics)
        if self.on_result:
            self.on_result(job_type, index, exit_code, diagnostics)
        return True

    def rpc_register_tensorboard_url(self, url: str) -> bool:
        self.session.tensorboard_url = url
        return True

    def rpc_register_callback_info(self, task_id: str, payload: str) -> bool:
        """Executor-pushed framework info (reference: registerCallbackInfo
        feeding Framework.ApplicationMasterAdapter.receiveTaskCallbackInfo).
        Recorded on the session and dispatched to the AM adapter hook."""
        self.session.task_callback_info[task_id] = payload
        if self.on_callback_info:
            self.on_callback_info(task_id, payload)
        return True

    def rpc_metrics_report(self, job_type: str, index: int,
                           metrics: Dict[str, float]) -> bool:
        task = self.session.task(job_type, index)
        sample = task.record_metrics(metrics)
        if self.on_metrics:
            # The sample, not the cumulative dict: a TASK_METRICS event is
            # one TaskMonitor reading, and stale keys must not reappear
            # with fresh timestamps in the portal timeline.
            self.on_metrics(job_type, index, sample)
        return True

    # -- client-facing verbs ----------------------------------------------
    def rpc_get_task_infos(self) -> list:
        return self.session.task_infos()

    def rpc_serve_endpoints(self, job_type: Optional[str] = None) -> list:
        """The routable replica set (tony_tpu.serve.router): serving
        tasks with reported telemetry, in task_infos wire form — the
        router derives each live replica's dial address from
        ``host`` + the heartbeat-carried ``rpc_port`` and retires
        terminal entries. Default spans EVERY serve-role jobtype (the
        disaggregated prefill/decode gangs included); pass a jobtype to
        scope."""
        return self.session.serve_endpoints(job_type)

    def rpc_get_task_callback_info(self) -> Dict[str, str]:
        """The per-task pushed callback payloads (e.g. profiler endpoints) —
        consumed by ``tony profile`` to find live trace servers."""
        return dict(self.session.task_callback_info)

    def rpc_get_job_status(self) -> Dict[str, Any]:
        return {
            "status": self.session.job_status.value,
            "message": self.session.final_message,
            "attempt_id": self.session.attempt_id,
            "tensorboard_url": self.session.tensorboard_url,
            "all_running_latency_s": self.session.all_running_latency_s,
        }

    def rpc_finish_application(self, reason: str = "killed by client") -> bool:
        from tony_tpu.session import JobStatus
        with self.session.lock:
            if self.session.job_status == JobStatus.RUNNING:
                self.session.job_status = JobStatus.KILLED
                self.session.final_message = reason
        self.session.kill_remaining(reason)
        return True
