"""Trace collection: fetch device/host traces from live profiler endpoints
into the job's history dir (SURVEY.md §5.1 — the TPU-build commitment is
"hook + trace collection to the history dir"; the hook half lives in
:mod:`tony_tpu.distributed`, this is the collection half).

The reference's equivalent surface is per-framework (TensorBoard reading a
profile plugin dir); here every rank's user process runs
``jax.profiler.start_server`` on the port the JAXRuntime assigned, the
executor pushes ``host:port`` to the AM via ``register_callback_info``, and
this module pulls a trace from each endpoint over the XLA profiler gRPC
service into ``<history>/traces/<app_id>/<task_id>/`` — next to the jhist,
where the history portal lists it.

Two triggers, both optional:

* ``tony profile <app_id>`` (client-side, any time while the job runs);
* ``tony.task.profiler.collect-after-s`` (AM-side: one automatic capture
  N seconds after the gang reaches RUNNING).

The capture client is xprof's (version-matched to jax's tsl profiler
service in this image); explicit tracer levels are passed because the
defaults collect nothing from a remote jax server.
"""

from __future__ import annotations

import logging
import sys
from pathlib import Path
from typing import Dict, List, Optional

# Tracer levels: host TraceMe spans + python + device. Without these the
# remote session returns "no trace data" (measured, not hypothetical).
_TRACE_OPTIONS = {
    "host_tracer_level": 2,
    "python_tracer_level": 1,
    "device_tracer_level": 1,
}


def _snapshot(store: Dict[str, Dict[str, object]]
              ) -> Dict[str, Dict[str, object]]:
    """THE report contract, shared by every registry below: a deep copy of
    the store — including nested per-level/per-bucket lists — so callers
    can serialize or mutate a report without poisoning the live records
    (the report schemas had drifted; ``tests/test_sched.py`` pins all of
    them on this one helper)."""
    import copy

    return {k: copy.deepcopy(v) for k, v in store.items()}


# ---------------------------------------------------------------------------
# Overlap-engine instrumentation (the comm/compute overlap tentpole): the
# engine's planners call :func:`record_overlap` at TRACE time — once per
# compile, not per step — so per-bucket collective sizes and schedule tick
# counts are inspectable next to the xplane traces without parsing HLO.
# Keyed by tag ("accum_step", "gpipe", "gpipe_1f1b"); last plan per tag
# wins (a recompile IS a new plan). Hierarchical/ZeRO-3 plans additionally
# carry a ``levels`` list — one entry per reduction level ("ici"/"dcn")
# with the collective op, its mesh axes, and the bytes each bucket moves
# AT THAT LEVEL (the DCN entry shows the scattered-chunk sizes, i.e. what
# actually crosses slices per bucket).
OVERLAP_RECORDS: Dict[str, Dict[str, object]] = {}


def record_overlap(tag: str, **fields) -> None:
    """Bank one overlap plan/schedule record (bucket count & bytes,
    microbatches, reduce op, per-level plans, schedule tick count...)."""
    OVERLAP_RECORDS[tag] = dict(fields)


def overlap_report() -> Dict[str, Dict[str, object]]:
    """Snapshot of every recorded overlap plan (deep-copied via
    :func:`_snapshot`: callers serialize this into bench/metrics JSON and
    must not alias the live registry)."""
    return _snapshot(OVERLAP_RECORDS)


def reset_overlap_records() -> None:
    OVERLAP_RECORDS.clear()


# ---------------------------------------------------------------------------
# Unified collective instrumentation (the collective-scheduler tentpole):
# ONE record schema for every inter-chip transfer the step issues —
# forward param gathers, gradient scatter/allreduce buckets, MoE expert
# all_to_all, pipeline ppermute edges — so "every collective is either
# hidden or accounted for" is inspectable from one report instead of four
# plane-specific ones. Writers go through :mod:`tony_tpu.parallel.sched`
# (``record_collective``); keyed by tag, last plan per tag wins. Schema
# (enforced by the sched-side writer, not here):
#   kind   — all_gather | psum_scatter | all_reduce | all_to_all | ppermute
#   plane  — fwd_gather | grad_reduce | moe | pipeline
#   axes   — mesh axes the collective runs over
#   nbytes — per-issue payload bytes (list)
# plus freeform extras (prefetch depth, level, chunk count, measured
# hidden/exposed seconds from the bench legs...).
COLLECTIVE_RECORDS: Dict[str, Dict[str, object]] = {}


def record_collective(tag: str, /, **fields) -> None:
    """Bank one collective schedule record under the unified schema."""
    COLLECTIVE_RECORDS[tag] = dict(fields)


def collective_report() -> Dict[str, Dict[str, object]]:
    """Snapshot of every scheduled collective (deep-copied via
    :func:`_snapshot` — same aliasing contract as the other reports)."""
    return _snapshot(COLLECTIVE_RECORDS)


def reset_collective_records() -> None:
    COLLECTIVE_RECORDS.clear()


# ---------------------------------------------------------------------------
# Checkpoint-plane instrumentation (tony_tpu.ckpt): the async snapshot
# engine records per-save timing — the stall the train loop actually paid
# (slot wait + device→host extract) vs the background write/commit time —
# keyed by tag ("async_save", "blocking_save"); last save per tag wins.
# run_ckpt_bench serializes this next to the overlap records so "async
# saves overlap training" is a measured number, not a design claim.
CKPT_RECORDS: Dict[str, Dict[str, object]] = {}


def record_ckpt(tag: str, **fields) -> None:
    """Bank one checkpoint-save record (stall/extract/write seconds,
    payload bytes, chunk count...)."""
    CKPT_RECORDS[tag] = dict(fields)


def ckpt_report() -> Dict[str, Dict[str, object]]:
    """Snapshot of every recorded checkpoint save (deep-copied via
    :func:`_snapshot` — same aliasing contract as
    :func:`overlap_report`)."""
    return _snapshot(CKPT_RECORDS)


def reset_ckpt_records() -> None:
    CKPT_RECORDS.clear()


# ---------------------------------------------------------------------------
# Input-plane instrumentation (tony_tpu.data): the prefetching device
# iterator records, per delivered batch, the time the train loop actually
# blocked waiting on the feed (the input stall — the transfer T3 says must
# hide under compute) plus rolling means of wait and host→device placement
# time. Keyed by iterator tag (default "input"); last step per tag wins.
# run_input_bench serializes this next to the overlap/ckpt records so
# "prefetch hides the feed" is a measured number (BENCH_r08).
INPUT_RECORDS: Dict[str, Dict[str, object]] = {}


def record_input(tag: str, **fields) -> None:
    """Bank one input-feed record (prefetch depth, steps, last/total wait
    seconds, mean wait/placement ms...)."""
    INPUT_RECORDS[tag] = dict(fields)


def input_report() -> Dict[str, Dict[str, object]]:
    """Snapshot of every recorded input feed (deep-copied via
    :func:`_snapshot` — same aliasing contract as
    :func:`overlap_report`)."""
    return _snapshot(INPUT_RECORDS)


def reset_input_records() -> None:
    INPUT_RECORDS.clear()


# ---------------------------------------------------------------------------
# Fused-optimizer instrumentation (tony_tpu.ops.fused_optim): the update
# plane records, at trace time, the bucket-major update schedule — bucket
# count and per-bucket payload bytes, which kernel path ran (pallas vs the
# pure-XLA fallback), the rule and its slot layout — keyed by tag
# ("accum_update" from the in-region accum path, "fused_update" from the
# standalone step); last plan per tag wins. run_optim_bench serializes
# this next to the overlap records so "one launch per bucket" is an
# inspectable number, not a design claim.
UPDATE_RECORDS: Dict[str, Dict[str, object]] = {}


def record_update(tag: str, /, **fields) -> None:
    """Bank one fused-optimizer update record (rule, impl, bucket count &
    bytes, slot layout, clip/decay config...)."""
    UPDATE_RECORDS[tag] = dict(fields)


def update_report() -> Dict[str, Dict[str, object]]:
    """Snapshot of every recorded update schedule (deep-copied via
    :func:`_snapshot` — same aliasing contract as the other reports)."""
    return _snapshot(UPDATE_RECORDS)


def reset_update_records() -> None:
    UPDATE_RECORDS.clear()


# ---------------------------------------------------------------------------
# Quantized-lane instrumentation (tony_tpu.ops.quant): the int8 lane
# records, at trace time, where quantization actually happened — per
# quant_dot call site (shapes, impl, per-channel, int8 vs bf16 operand
# bytes), the quantize-on-gather schedule (bucket count, delayed-scaling
# window, raw vs int8 wire bytes = the 4×-fewer-gather-bytes claim as an
# inspectable number), and the attach-time state geometry. Keyed by tag
# ("dense.<name>", "accum_gather", "attach"); last plan per tag wins.
# run_quant_bench serializes this next to the other records (BENCH_r11).
QUANT_RECORDS: Dict[str, Dict[str, object]] = {}


def record_quant(tag: str, /, **fields) -> None:
    """Bank one quantized-lane record (matmul shapes/impl, scale-window
    geometry, gather bytes saved...)."""
    QUANT_RECORDS[tag] = dict(fields)


def quant_report() -> Dict[str, Dict[str, object]]:
    """Snapshot of every recorded quantization site (deep-copied via
    :func:`_snapshot` — same aliasing contract as the other reports)."""
    return _snapshot(QUANT_RECORDS)


def reset_quant_records() -> None:
    QUANT_RECORDS.clear()


# ---------------------------------------------------------------------------
# Serving-plane instrumentation (tony_tpu.serve): the engine records its
# build-time geometry (context extent, block pool size, row block,
# decode buckets, join policy) under the engine tag and its live
# telemetry — the heartbeat triple qps/p99/queue-depth plus rates, and
# since the speculative lane (serve.spec) also tokens_per_forward,
# acceptance_rate, proposed/accepted token counts, and verify-launch
# counts — under "<tag>_stats"; the speculative geometry (draft kind,
# depth k) under "<tag>_spec"; the replica banks restore geometry under
# "replica". Keyed by tag; last record per tag wins. run_serve_bench /
# run_spec_bench serialize this next to the other records
# (BENCH_r12/r13).
SERVE_RECORDS: Dict[str, Dict[str, object]] = {}


def record_serve(tag: str, /, **fields) -> None:
    """Bank one serving-plane record (engine geometry, qps/p50/p99/
    queue-depth telemetry, replica restore geometry...)."""
    SERVE_RECORDS[tag] = dict(fields)


def serve_report() -> Dict[str, Dict[str, object]]:
    """Snapshot of every recorded serving-plane entry (deep-copied via
    :func:`_snapshot` — same aliasing contract as the other reports)."""
    return _snapshot(SERVE_RECORDS)


def reset_serve_records() -> None:
    SERVE_RECORDS.clear()


# ---------------------------------------------------------------------------
# Static-analysis instrumentation (tony_tpu.analysis): the jaxpr analyzer
# banks one record per analyzed step — finding counts by rule, waived
# count, the step-signature digest (eqn/collective counts, live-buffer
# high-water estimate) — keyed by analysis tag (the config name passed to
# `tony analyze` / analyze_accum_step); last run per tag wins. This is the
# machine-readable face of `analysis_report()` the ISSUE names alongside
# the existing report family.
ANALYSIS_RECORDS: Dict[str, Dict[str, object]] = {}


def record_analysis(tag: str, /, **fields) -> None:
    """Bank one static-analysis record (findings by rule, waived count,
    signature digest, collective census...)."""
    ANALYSIS_RECORDS[tag] = dict(fields)


def analysis_report() -> Dict[str, Dict[str, object]]:
    """Snapshot of every recorded analysis run (deep-copied via
    :func:`_snapshot` — same aliasing contract as the other reports)."""
    return _snapshot(ANALYSIS_RECORDS)


def reset_analysis_records() -> None:
    ANALYSIS_RECORDS.clear()


# ---------------------------------------------------------------------------
# Lock-witness instrumentation (tony_tpu.analysis.concurrency): the runtime
# witness banks the process-global observed lock-order graph — every (held,
# acquired) edge any thread produced through an instrumented
# Lock/RLock/Condition, with counts, thread names, and first-observation
# sites — under tag "witness" (re-banked whenever a NEW edge appears), and
# the concurrency lint banks its summary next to the jaxpr analyzer's in
# analysis_report(). Cycle detection over this graph merged with the static
# nested-`with` graph is what turns a potential deadlock into a named
# finding instead of a hung CI job.
LOCK_RECORDS: Dict[str, Dict[str, object]] = {}


def record_locks(tag: str, /, **fields) -> None:
    """Bank one lock-witness record (instrumented lock names, observed
    acquisition-order edges with counts/threads/sites...)."""
    LOCK_RECORDS[tag] = dict(fields)


def lock_report() -> Dict[str, Dict[str, object]]:
    """Snapshot of every recorded lock-witness entry (deep-copied via
    :func:`_snapshot` — same aliasing contract as the other reports)."""
    return _snapshot(LOCK_RECORDS)


def reset_lock_records() -> None:
    LOCK_RECORDS.clear()


# One guarded entry point for the trace-side recorders (overlap grad sync,
# ckpt snapshot, input prefetch): bookkeeping must never sink a step or a
# save, and a broken wiring is logged once per registry at DEBUG — not per
# trace — so it stays diagnosable without log spam.
_SAFE_RECORD_FAILED: set = set()


def safe_record(kind: str, tag: str, /, **fields) -> None:
    """Record into the ``kind`` registry (``"overlap"``/``"ckpt"``/
    ``"input"``/``"collective"``/``"update"``/``"quant"``/
    ``"serve"``/``"analysis"``/``"locks"``), swallowing any failure."""
    try:
        {"overlap": record_overlap, "ckpt": record_ckpt,
         "input": record_input, "collective": record_collective,
         "update": record_update, "quant": record_quant,
         "serve": record_serve, "analysis": record_analysis,
         "locks": record_locks}[kind](
             tag, **fields)
    except Exception:  # noqa: BLE001
        if kind not in _SAFE_RECORD_FAILED:
            _SAFE_RECORD_FAILED.add(kind)
            logging.getLogger(__name__).debug(
                "%s profiler record %r failed; further failures "
                "suppressed", kind, tag, exc_info=True)


def _trace_fn():
    """Resolve a capture callable ``(addr, logdir, duration_ms) -> None``.
    Import is deferred and gated: the profiler client is an optional
    dependency and must not tax AM/executor startup."""
    try:
        from xprof.convert import _pywrap_profiler_plugin as pp

        def capture(addr: str, logdir: str, duration_ms: int) -> None:
            pp.trace(addr, logdir, "", True, duration_ms, 3, _TRACE_OPTIONS)

        return capture
    except ImportError:
        pass
    try:
        from tensorflow.python.profiler import profiler_client

        def capture(addr: str, logdir: str, duration_ms: int) -> None:
            # TF >= 2.16 requires a ProfilerOptions namedtuple (it calls
            # options._asdict()); a plain dict dies inside the client
            # with "'dict' object has no attribute '_asdict'" — measured
            # on this image's TF 2.20, where it broke every capture.
            options: object = _TRACE_OPTIONS
            try:
                from tensorflow.python.profiler.profiler_v2 import (
                    ProfilerOptions)
                options = ProfilerOptions(**{
                    k: v for k, v in _TRACE_OPTIONS.items()
                    if k in ProfilerOptions._fields})
            except ImportError:
                pass
            profiler_client.trace(f"grpc://{addr}", logdir, duration_ms,
                                  options=options)

        return capture
    except ImportError:
        return None


def traces_root(history_dir: str | Path, app_id: str) -> Path:
    return Path(history_dir) / "traces" / app_id


def endpoints_from_callback_info(info: Dict[str, str]) -> Dict[str, str]:
    """``{task_id: host:port}`` of live profiler servers, from the per-task
    callback payloads the executors pushed (``register_callback_info``)."""
    import json

    out: Dict[str, str] = {}
    for task_id, payload in dict(info).items():
        try:
            parsed = json.loads(payload)
        except ValueError:
            continue
        if isinstance(parsed, dict) and "profiler" in parsed:
            out[task_id] = str(parsed["profiler"])
    return out


def _wait_reachable(addr: str, timeout_s: float) -> bool:
    """Poll until ``host:port`` accepts TCP. The executor pushes the
    endpoint at user-process LAUNCH — the profiler server inside it only
    starts listening after the jax import, seconds later."""
    import socket
    import time

    host, _, port = addr.rpartition(":")
    host = host.strip("[]")   # "[::1]:9431" → host "::1"
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            socket.create_connection((host, int(port)), timeout=2.0).close()
            return True
        except OSError:
            time.sleep(0.25)
    return False


def collect_traces(endpoints: Dict[str, str], history_dir: str | Path,
                   app_id: str, duration_ms: int = 2000,
                   wait_reachable_s: float = 60.0, log=print) -> List[Path]:
    """Capture ONE synchronized trace session across every reachable
    endpoint into ``<history>/traces/<app_id>/`` (one capture call over
    the comma-joined address list — per-rank windows align in time, which
    is the whole point of profiling cross-host collectives; a sequential
    per-rank loop would give disjoint windows). A ``manifest.json``
    records task_id → endpoint so the portal can attribute the per-host
    xplane files. Unreachable ranks are reported and dropped from the
    session — a partial profile beats none."""
    import json

    capture = _trace_fn()
    if capture is None:
        log("trace collection unavailable: no profiler client "
            "(xprof / tensorflow) importable", file=sys.stderr)
        return []
    live = {}
    for task_id, addr in sorted(endpoints.items()):
        if _wait_reachable(addr, wait_reachable_s):
            live[task_id] = addr
        else:
            log(f"trace capture from {task_id} ({addr}) skipped: "
                f"endpoint not reachable within {wait_reachable_s:.0f}s")
    if not live:
        return []
    # Absolute: the logdir travels inside the profiler RPC and the SERVER
    # (the profiled process, different cwd) writes the xplane files — a
    # relative path silently lands in (or fails under) the wrong tree.
    dest = traces_root(history_dir, app_id).resolve()
    dest.mkdir(parents=True, exist_ok=True)
    (dest / "manifest.json").write_text(json.dumps(live, sort_keys=True))
    # A capture landing in a dead window (the job mid-compile, between
    # steps) legitimately returns zero events; retry a couple of times
    # before giving up — the operator asked for a trace, not for luck.
    # Success means a NEW xplane file: .pb files from an earlier capture
    # into the same dest must not mask an empty session.
    import time
    before = {p for p in dest.rglob("*") if p.suffix == ".pb"}
    for attempt in range(3):
        try:
            capture(",".join(live.values()), str(dest), duration_ms)
        except Exception as e:  # noqa: BLE001 — profiling is advisory
            log(f"trace capture from {sorted(live)} failed: {e}")
            return []
        if {p for p in dest.rglob("*") if p.suffix == ".pb"} - before:
            log(f"synchronized trace from {sorted(live)} -> {dest}")
            return [dest]
        log(f"trace capture from {sorted(live)} produced no events "
            f"(attempt {attempt + 1}/3; job idle or compiling?)")
        if attempt < 2:
            time.sleep(2.0)
    return []


def list_traces(history_dir: str | Path,
                app_id: str) -> Dict[str, List[Dict[str, object]]]:
    """Collected trace files per task, for the portal/CLI:
    ``{task_id: [{file, bytes}, ...]}``. Files are attributed to tasks by
    matching the manifest's endpoint (``host_port`` appears in the xplane
    filename); unattributed files land under ``"session"``."""
    import json

    root = traces_root(history_dir, app_id)
    if not root.is_dir():
        return {}
    manifest: Dict[str, str] = {}
    mpath = root / "manifest.json"
    if mpath.is_file():
        try:
            manifest = json.loads(mpath.read_text())
        except ValueError:
            pass
    by_task: Dict[str, List[Dict[str, object]]] = {}
    for p in sorted(root.rglob("*")):
        if not p.is_file() or p.name == "manifest.json":
            continue
        entry = {"file": str(p.relative_to(root)), "bytes": p.stat().st_size}
        owner = "session"
        for task_id, addr in manifest.items():
            # Brackets never appear in xplane filenames — "[::1]:9431"
            # must match as "__1_9431", not "[__1]_9431".
            if addr.replace("[", "").replace("]", "") \
                    .replace(":", "_") in p.name:
                owner = task_id.replace(":", "_")
                break
        by_task.setdefault(owner, []).append(entry)
    return by_task
