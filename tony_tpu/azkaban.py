"""Azkaban-style job-file submission shim.

Mirrors ``tony-azkaban`` (upstream ``tony-azkaban/src/main/java/com/linkedin/
tony/azkaban/TonyJob.java``, unverified — SURVEY.md §0/§2.2): the scheduler
plugin that turns a declarative job file (``type=TonYJob`` + java-properties
key/values) into a TonY submission. Here the shim is scheduler-agnostic —
any workflow engine that can run a shell command uses::

    tony azkaban myjob.job

Job-file keys map as in the reference plugin: every ``tony.*`` property
passes through to the job config verbatim; the Azkaban-side wrapper keys
translate to their client flags (``src.dir`` → ``--src_dir``,
``hadoop.command`` / ``executes`` → the task command).
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, Optional

from tony_tpu import conf as conf_mod
from tony_tpu.conf import TonyConfig

# Azkaban wrapper-key → config-key translation (non-"tony." keys).
_WRAPPER_KEYS = {
    "executes": "tony.application.executes",
    "hadoop.command": "tony.application.executes",
    "job.name": conf_mod.APPLICATION_NAME,
    "framework": conf_mod.APPLICATION_FRAMEWORK,
    "python.venv": conf_mod.PYTHON_VENV,
    "python.binary.path": conf_mod.PYTHON_BINARY,
}


def parse_job_file(path: str | Path) -> Dict[str, str]:
    """Java-properties parser: ``key=value`` lines, ``#``/``!`` comments,
    trailing-backslash continuations (the format Azkaban job files use)."""
    props: Dict[str, str] = {}
    pending = ""
    for raw in Path(path).read_text().splitlines():
        line = pending + raw.strip()
        pending = ""
        if not line or line[0] in "#!":
            continue
        if line.endswith("\\"):
            pending = line[:-1]
            continue
        if "=" not in line:
            continue
        key, _, value = line.partition("=")
        props[key.strip()] = value.strip()
    return props


def job_file_conf(path: str | Path) -> tuple[TonyConfig, Optional[str]]:
    """(config, src_dir) from a job file: ``tony.*`` keys pass through,
    wrapper keys translate (reference: ``TonyJob#getJobProps``)."""
    props = parse_job_file(path)
    cfg = TonyConfig()
    src_dir = props.get("src.dir") or props.get("working.dir")
    for key, value in props.items():
        if key.startswith("tony."):
            cfg.set(key, value)
        elif key in _WRAPPER_KEYS:
            cfg.set(_WRAPPER_KEYS[key], value)
    return cfg, src_dir


def main(args) -> int:
    from tony_tpu.client import TonyClient
    cfg, src_dir = job_file_conf(args.job_file)
    client = TonyClient(cfg, src_dir=src_dir,
                        workdir=getattr(args, "workdir", None))
    return client.run(timeout=getattr(args, "timeout", None))
