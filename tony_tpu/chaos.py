"""Chaos-injection harness: scripted control-plane faults (jax-free).

Extends the ``TONY_CKPT_CRASH`` idiom (:mod:`tony_tpu.ckpt.format`) from
one checkpoint-commit fault to a vocabulary the whole control plane
consults, so the elastic-resize pins are machine-checkable: a test (or
``bench.py``) arms a fault schedule through ``TONY_CHAOS_*`` env vars
and the production code paths fire it at the instrumented sites —

* ``TONY_CHAOS_KILL_STEP=k`` — SIGKILL this process as TRAINING step
  ``k`` begins (:func:`tony_tpu.train.train_loop` consults
  :func:`kill_point` each step): the scripted preemption.
* ``TONY_CHAOS_HB_DROP=n`` — swallow the first ``n`` executor heartbeat
  sends (:func:`drop_heartbeat`): a flaky heartbeat window that must NOT
  mark a healthy task lost now that the RPC client backs off and
  retries.
* ``TONY_CHAOS_RPC_DELAY_S=s`` (+ optional ``TONY_CHAOS_RPC_DELAY_CALLS=n``,
  default 1) — stall the first ``n`` RPC calls ``s`` seconds before they
  touch the wire (:func:`rpc_delay` in ``RpcClient.call``): transient
  transport latency.
* ``TONY_CHAOS_CRASH=<site>`` — SIGKILL at a named crash site
  (:func:`crash_point`); the history-plane rotation path declares
  ``rotate_before_stage`` / ``rotate_after_stage`` / ``rotate_after_replace``
  so the stage-and-rename sweep can prove "old log or new log, never a
  torn file"; the continuous-publication plane declares
  ``publish_before_stage`` / ``publish_after_stage`` /
  ``publish_after_replace`` around the pointer-file commit
  (:func:`tony_tpu.publish.publish_step`) and ``swap_before_restore`` /
  ``swap_after_restore`` / ``swap_before_flip`` / ``swap_after_flip``
  around a replica's hot swap (:meth:`tony_tpu.serve.replica.Replica.
  hot_swap`) so the sweep can prove "old weights or new weights, never
  a mixed-version replica". (Checkpoint commits keep their original
  ``TONY_CKPT_CRASH`` phases.)

Every probe is a cheap env read that no-ops when unarmed — an unarmed
process pays one ``os.environ.get`` per site. Malformed specs raise
``ValueError`` loudly: silently ignoring a typoed fault schedule would
turn a failing chaos test into a vacuous pass.

In-process tests can replace the irreversible faults with module hooks
(the ``CRASH_HOOK`` idiom): ``KILL_HOOK``/``CRASH_HOOK`` observe the
fault instead of delivering SIGKILL, ``SLEEP_HOOK`` replaces the delay
sleep. "First n" schedules count across call sites through a
lock-guarded module counter table — call :func:`reset` between tests.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Callable, Dict, Optional

__all__ = [
    "ENV_KILL_STEP", "ENV_HB_DROP", "ENV_RPC_DELAY_S",
    "ENV_RPC_DELAY_CALLS", "ENV_CRASH",
    "kill_point", "drop_heartbeat", "rpc_delay", "crash_point", "reset",
]

ENV_KILL_STEP = "TONY_CHAOS_KILL_STEP"
ENV_HB_DROP = "TONY_CHAOS_HB_DROP"
ENV_RPC_DELAY_S = "TONY_CHAOS_RPC_DELAY_S"
ENV_RPC_DELAY_CALLS = "TONY_CHAOS_RPC_DELAY_CALLS"
ENV_CRASH = "TONY_CHAOS_CRASH"

# Test hooks: when set, the hook fires INSTEAD of the real fault
# (SIGKILL / sleep), so in-process tests can observe or redirect it.
KILL_HOOK: Optional[Callable[[int], None]] = None
CRASH_HOOK: Optional[Callable[[str], None]] = None
SLEEP_HOOK: Optional[Callable[[float], None]] = None

_lock = threading.Lock()    # guards _counters (probe sites span threads)
_counters: Dict[str, int] = {}


def reset() -> None:
    """Clear the "first n" schedule counters (test epilogue)."""
    with _lock:
        _counters.clear()


def _count(key: str) -> int:
    with _lock:
        _counters[key] = _counters.get(key, 0) + 1
        return _counters[key]


def _int_env(name: str) -> Optional[int]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"chaos schedule {name}={raw!r} is not an integer") from None


def _float_env(name: str) -> Optional[float]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(
            f"chaos schedule {name}={raw!r} is not a number") from None
    if val != val or val < 0:
        raise ValueError(
            f"chaos schedule {name}={raw!r} must be >= 0")
    return val


def kill_point(step: int) -> None:
    """SIGKILL this process if ``TONY_CHAOS_KILL_STEP`` names ``step``
    (the scripted preemption: the scheduler's kill -9, not a clean
    exit). Consulted by ``train_loop`` as each step begins, so the kill
    lands AFTER the previous step's work and BEFORE any of step ``k``'s
    examples are consumed."""
    at = _int_env(ENV_KILL_STEP)
    if at is None or step != at:
        return
    if KILL_HOOK is not None:
        KILL_HOOK(step)
        return
    os.kill(os.getpid(), signal.SIGKILL)


def drop_heartbeat() -> bool:
    """True if this heartbeat send should be swallowed (the first ``n``
    probes when ``TONY_CHAOS_HB_DROP=n`` is armed)."""
    n = _int_env(ENV_HB_DROP)
    if n is None or n <= 0:
        return False
    return _count("hb_drop") <= n


def rpc_delay() -> None:
    """Stall the first ``TONY_CHAOS_RPC_DELAY_CALLS`` (default 1) RPC
    calls by ``TONY_CHAOS_RPC_DELAY_S`` seconds — injected transport
    latency, counted per logical call (retries of a delayed call are
    not re-delayed: the fault is the network hiccup, not a broken
    peer)."""
    delay = _float_env(ENV_RPC_DELAY_S)
    if delay is None or delay <= 0:
        return
    n = _int_env(ENV_RPC_DELAY_CALLS)
    if _count("rpc_delay") <= (1 if n is None else n):
        (SLEEP_HOOK or time.sleep)(delay)


def crash_point(site: str) -> None:
    """SIGKILL at a named crash site when ``TONY_CHAOS_CRASH`` matches —
    the ``TONY_CKPT_CRASH`` idiom generalized: production code declares
    the site, the test arms exactly one, and the invariant is whatever
    must survive a kill -9 there."""
    if os.environ.get(ENV_CRASH, "") != site:
        return
    if CRASH_HOOK is not None:
        CRASH_HOOK(site)
        return
    os.kill(os.getpid(), signal.SIGKILL)
