"""Continuous weight publication: the train->serve pointer plane.

PR 3 gave checkpoints an atomic commit (stage -> fsync -> rename, a
step directory counts only once its manifest is inside); PR 19 gave the
serve side an elastic restore that can land any committed manifest on a
live mesh. This module closes the loop between them with ONE small
durable artifact: a versioned pointer file ``published.json`` in the
checkpoint root, naming the committed step the serving fleet should be
running.

The pointer is the whole protocol:

* the TRAIN side (``train_loop``'s ``publish_every`` knob, or the
  ``tony publish`` CLI) advances it — only ever to a step that
  :func:`tony_tpu.ckpt.format.committed_steps` proves committed, and
  only through the same stage-and-rename idiom the ckpt commit itself
  uses, so a SIGKILL anywhere leaves the OLD pointer or the NEW one,
  never a torn file;
* the SERVE side (executor heartbeats via :func:`latest_publication`,
  the AM's rolling-swap tick, ``tony serve --follow``) reads it —
  jax-free and failure-silent, because a publication probe runs on
  every heartbeat and a half-visible NFS read must degrade to "no news"
  rather than kill the beat.

Versions are a monotonically increasing integer minted here (previous
pointer's version + 1, starting at 1), NOT the step number: a rollback
publication re-points at an OLDER step with a NEWER version, and the
fleet swap logic only ever compares versions. The chaos sites
(``publish_before_stage`` / ``publish_after_stage`` /
``publish_after_replace``) follow the history-rotation naming so the
crash sweep in tests/test_publish.py can prove the old-or-new claim at
each boundary.

Layering: jax-free at import (the control-plane rule) — this module is
read by the AM, the executor heartbeat loop, and the CLI, none of which
may drag in an accelerator stack.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Any, Dict, Optional

from tony_tpu import chaos
from tony_tpu.ckpt.format import MANIFEST_NAME, _fsync_dir, \
    committed_steps, step_dir

__all__ = ["PUBLISH_FILE", "PublishError", "publish_step",
           "latest_publication"]

# Lives in the checkpoint ROOT, next to the step_%08d dirs it points
# into — one rename away from every manifest it can name, so pointer
# and checkpoint are always on the same filesystem (os.replace must be
# atomic between them).
PUBLISH_FILE = "published.json"


class PublishError(RuntimeError):
    """The publication cannot be made (uncommitted step, missing ckpt
    root). Typed so callers distinguish "nothing to publish yet" from a
    broken pointer write — the CLI surfaces it as a clean error, the
    train loop as a hard fault (publishing an uncommitted step would
    hand the fleet a manifest that may never exist)."""


def publish_step(ckpt_dir: str | Path, step: Optional[int] = None, *,
                 note: str = "") -> Dict[str, Any]:
    """Advance the pointer to ``step`` (default: the newest committed
    step) and return the new record. The step MUST already be committed
    — the pointer may only ever name a manifest a restore can land, and
    the async checkpointer's caller is responsible for ``wait()``-ing
    its own commit before publishing it.

    Crash-safe by stage-and-rename: the tmp file is fsynced before the
    rename and the directory after it, and the three declared chaos
    sites bracket both moves. Re-publishing the same step mints a new
    version (an explicit re-push is a fleet-wide "converge again"
    signal, not a no-op).
    """
    root = Path(ckpt_dir)
    steps = committed_steps(root)
    if step is None:
        if not steps:
            raise PublishError(f"no committed checkpoint under {root} "
                               f"— nothing to publish")
        step = steps[-1]
    step = int(step)
    if step not in steps:
        raise PublishError(
            f"step {step} is not committed under {root} "
            f"(committed: {steps[-5:] if steps else []}) — a pointer "
            f"must only name a manifest a restore can land")
    prev = latest_publication(root)
    record = {
        "version": (int(prev["version"]) + 1) if prev else 1,
        "step": step,
        "manifest": f"{step_dir(root, step).name}/{MANIFEST_NAME}",
        "published_at": time.time(),
        "note": str(note),
    }
    target = root / PUBLISH_FILE
    tmp = root / (PUBLISH_FILE + ".tmp")
    chaos.crash_point("publish_before_stage")
    with open(tmp, "w") as f:
        json.dump(record, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    chaos.crash_point("publish_after_stage")
    os.replace(tmp, target)
    chaos.crash_point("publish_after_replace")
    _fsync_dir(root)
    return record


def latest_publication(ckpt_dir: str | Path) -> Optional[Dict[str, Any]]:
    """The current pointer record, or ``None`` when nothing was ever
    published (or the file is unreadable/malformed — failure-silent BY
    CONTRACT: this runs inside every executor heartbeat and the AM
    tick, where a transiently half-visible network filesystem must read
    as "no publication news", never kill the probe). A well-formed
    record always carries integer ``version`` and ``step``."""
    try:
        with open(Path(ckpt_dir) / PUBLISH_FILE) as f:
            rec = json.load(f)
        if not isinstance(rec, dict):
            return None
        rec["version"] = int(rec["version"])
        rec["step"] = int(rec["step"])
        return rec
    except (OSError, ValueError, TypeError, KeyError):
        return None
