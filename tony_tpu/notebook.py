"""Notebook submitter: one interactive container behind the TCP proxy.

Mirrors ``tony-cli``'s ``NotebookSubmitter`` (upstream ``tony-cli/src/main/
java/com/linkedin/tony/cli/NotebookSubmitter.java``, unverified — SURVEY.md
§0/§2.2): submit a single ``notebook`` task on the StandaloneRuntime, wait
for the task to come up and register its URL (the executor reserves the
``TB_PORT`` sidecar port and reports it via ``register_tensorboard_url``),
then run a local :class:`~tony_tpu.proxy.ProxyServer` so the gateway user can
reach it. The notebook command should bind ``$TB_PORT``.
"""

from __future__ import annotations

from tony_tpu import conf as conf_mod
from tony_tpu.cli import _parse_conf_overrides
from tony_tpu.client import TonyClient
from tony_tpu.conf import TonyConfig
from tony_tpu.proxy import ProxyServer


def main(args) -> int:
    cfg = TonyConfig()
    if getattr(args, "conf_file", None):
        cfg.merge_file(args.conf_file)
    cfg.set(conf_mod.APPLICATION_FRAMEWORK, "standalone")
    cfg.set("tony.notebook.instances", "1")
    cfg.set("tony.notebook.command", args.executes)
    # The notebook IS the job here: track it so its exit (clean shutdown or
    # crash) ends the application with its exit code — an all-untracked
    # session would never reach a final status and hang the CLI.
    untracked = [t for t in cfg.untracked_job_types() if t != "notebook"]
    cfg.set(conf_mod.APPLICATION_UNTRACKED, ",".join(untracked))
    cfg.merge_overrides(_parse_conf_overrides(args.conf or []))
    client = TonyClient(cfg, src_dir=args.src_dir, workdir=args.workdir)
    proxy_holder: dict = {}

    def on_update(infos) -> None:
        if proxy_holder or client.tensorboard_url is None:
            return
        url = client.tensorboard_url  # http://host:port
        hostport = url.split("//", 1)[-1]
        host, _, port = hostport.rpartition(":")
        proxy = ProxyServer(host or "127.0.0.1", int(port),
                            local_port=args.port).start()
        proxy_holder["proxy"] = proxy
        print(f"notebook reachable at http://127.0.0.1:{proxy.local_port}/ "
              f"(proxied to {hostport})", flush=True)

    client.add_listener(on_update)
    try:
        return client.run()
    finally:
        proxy = proxy_holder.get("proxy")
        if proxy is not None:
            proxy.stop()
