"""Unified collective scheduler: one tracking-and-triggering layer over
every inter-chip transfer in a train step.

T3 (arXiv:2401.16677) argues that fine-grained compute/collective overlap
needs ONE layer that owns all transfers, not a per-collective hack — the
same consolidation Horovod (arXiv:1802.05799) made for GPU reductions.
After PRs 1–2 this repo had the backward half: :class:`~tony_tpu.parallel
.overlap.GradBuckets` schedules the gradient reduce. This module promotes
that planner into the general scheduler the ROADMAP names:

* :class:`GatherPlan` — the forward-path twin of the backward scatter.
  ZeRO-3 param ``all_gather``s used to run per leaf and unbucketed; here
  they are coalesced into the SAME shard-major byte-threshold buckets the
  scatter plan uses (one ``all_gather`` per bucket returns the buffer in
  exactly the layout ``GradBuckets.pack`` writes, so
  ``leaf_buffers(layout="gathered")`` unpacks whole leaves — pure data
  movement, bit-exact vs per-leaf gathers). A ``prefetch`` depth chains
  bucket *k*'s gather on bucket *k−prefetch*'s completion via
  ``lax.optimization_barrier``: XLA's latency-hiding scheduler slides
  bucket *k+1*'s gather under bucket *k*'s layer compute, but can never
  hoist EVERY gather to step start — so replicated params only
  materialize for the live window of buckets, preserving the ZeRO-3
  memory contract.
* :func:`moe_dispatch_ffn_combine` — MoE expert dispatch/combine with the
  EP ``all_to_all`` issued EXPLICITLY per capacity chunk inside the layer
  (instead of whatever GSPMD picks for the dispatch einsum): chunk *c+1*'s
  dispatch a2a is dataflow-independent of chunk *c*'s expert FFN, so the
  a2a rides under FFN compute. Math mirrors
  :class:`tony_tpu.models.moe.MoEMLP`'s GSPMD path (same einsums, same
  dtype casts) up to the fp reassociation of the per-chunk combine sum.
* :func:`record_pipeline_edges` — registers ``gpipe``/``gpipe_1f1b``'s
  ``ppermute`` ring edges with the scheduler so pipeline traffic shares
  the same profiler record schema as everything else.
* :func:`record_collective` / ``profiler.collective_report()`` — the one
  record schema (kind, plane, axes, per-issue nbytes + freeform extras):
  every collective in a ZeRO-3 + MoE + pipeline step is either hidden or
  accounted for, inspectable from one report.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tony_tpu import compat
from tony_tpu._trace import trace_record
from tony_tpu.parallel import DATA, EXPERT, FSDP, MODEL, PIPE, SEQ, SLICE
from tony_tpu.parallel.overlap import GradBuckets

# Forward-gather prefetch depth: how many bucket gathers may be in flight
# ahead of the one compute is consuming. 1 = classic double buffering (the
# next bucket gathers while this one computes); 0 disables the chain (all
# gathers issue eagerly — max overlap, max transient replicated memory).
DEFAULT_PREFETCH = 1

# Trace-time side channel into the unified profiler registry (same shim
# contract as overlap's _record: lazy import, swallow-all, log-once).
record_collective = functools.partial(trace_record, "collective")


@dataclass(frozen=True)
class GatherPlan:
    """Bucketed + prefetched forward ``all_gather`` schedule over a ZeRO-3
    :class:`GradBuckets` plan.

    Everything here is resolved at BUILD time, outside any trace (the
    per-call spec probing that used to live in ``gather_params`` is
    hoisted into :meth:`from_buckets`):

    * ``gather_buckets`` — the plan's even (unpadded) scatter buckets in
      leaf-consumption order: these hold exactly the leaves that cross
      the manual region in the shard layout and need gathering.
    * ``gather_leaves`` — ``(leaf_index, shard_dim)`` pairs for the same
      leaves, the static drive list of the per-leaf fallback path.
    * ``passthrough`` — leaf indices NOT gathered: replicated leaves,
      scalars, and uneven (padded) leaves, which enter the region whole.
    """

    plan: GradBuckets
    prefetch: int = DEFAULT_PREFETCH
    axis: str = FSDP
    gather_buckets: Tuple[int, ...] = ()
    gather_leaves: Tuple[Tuple[int, int], ...] = ()
    passthrough: Tuple[int, ...] = ()

    @classmethod
    def from_buckets(cls, plan: GradBuckets, *,
                     prefetch: int = DEFAULT_PREFETCH,
                     axis: str = FSDP) -> "GatherPlan":
        if prefetch < 0:
            raise ValueError(f"prefetch must be >= 0, got {prefetch}")
        gatherable = set()
        buckets = []
        for b in range(plan.n_buckets):
            if plan._is_scatter(b) and not plan._is_padded(b):
                buckets.append(b)
                gatherable.update(plan.buckets[b])
        # Consumption order: leaves flatten in model order, so the bucket
        # holding the earliest leaf is the one compute touches first —
        # gather in that order or the prefetch chain fights the consumer.
        buckets.sort(key=lambda b: min(plan.buckets[b]))
        leaves = tuple(
            (i, plan.shard_dims[i]) for i in range(len(plan.shapes))
            if i in gatherable)
        passthrough = tuple(i for i in range(len(plan.shapes))
                            if i not in gatherable)
        return cls(plan, prefetch, axis, tuple(buckets), leaves,
                   passthrough)

    @property
    def n_gather_buckets(self) -> int:
        return len(self.gather_buckets)

    @property
    def gather_nbytes(self) -> Tuple[int, ...]:
        """Per-gather payload bytes (the FULL gathered buffer — what the
        collective materializes, shard_size × what each chip sends)."""
        return tuple(self.plan.bucket_nbytes[b] for b in self.gather_buckets)

    def window_nbytes(self) -> int:
        """The prefetch-window memory promise: the most replicated bytes
        the chained gathers may have in flight at once — bucket *k* can't
        issue before bucket *k − prefetch* exists, so at most
        ``prefetch + 1`` consecutive gathered buffers coexist as fresh
        gathers (``prefetch = 0`` disables the chain: everything may
        issue eagerly). This is the bound the analyzer's replication-leak
        rule audits the traced step against."""
        sizes = self.gather_nbytes
        if not sizes:
            return 0
        if not self.prefetch:
            return sum(sizes)
        width = min(len(sizes), self.prefetch + 1)
        return max(sum(sizes[k:k + width])
                   for k in range(len(sizes) - width + 1))

    def gather(self, leaves: Sequence[jax.Array],
               scales: Optional[Sequence[jax.Array]] = None
               ) -> List[jax.Array]:
        """Region-local leaves (shard layout) → full leaves, one
        ``all_gather`` per bucket, prefetch-chained. Must be called inside
        a manually-sharded region over ``self.axis``.

        ``scales`` (one f32 scalar per gather bucket, IDENTICAL on every
        shard — the quantized lane's delayed scales) switches the wire
        format to int8: each chunk is symmetric-quantized before the
        collective and dequantized on arrival, so the gather ships
        ``itemsize×`` fewer bytes (4× for f32 params). Because the scale
        is shared, quantize∘gather ≡ gather∘quantize bit-exact — see
        :mod:`tony_tpu.ops.quant`."""
        plan = self.plan
        out = list(leaves)
        done: List[jax.Array] = []
        for k, b in enumerate(self.gather_buckets):
            idxs = plan.buckets[b]
            parts = [leaves[i].reshape(-1) for i in idxs]
            # packsite: region-local — inside the shard_map region these
            # are per-device shard buffers, never GSPMD-sharded arrays.
            chunk = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            if self.prefetch and k >= self.prefetch:
                # Bucket k may not start gathering before bucket
                # k-prefetch's buffer exists: bounds in-flight replicated
                # bytes without serializing gather k behind its consumer.
                dep = done[k - self.prefetch].reshape(-1)[0]
                chunk, _ = jax.lax.optimization_barrier((chunk, dep))
            if scales is not None:
                from tony_tpu.ops.quant import dequantize, quantize

                q = jax.lax.all_gather(quantize(chunk, scales[k]),
                                       self.axis, tiled=True)
                full = dequantize(q, scales[k], chunk.dtype)
            else:
                full = jax.lax.all_gather(chunk, self.axis, tiled=True)
            done.append(full)
            # The gathered buffer is shard-major — exactly pack()'s scatter
            # layout — so the uneven-leaf exit path's "gathered" unpacking
            # is the inverse for free (no pads here: padded buckets are
            # passthrough).
            for i, v in plan.leaf_buffers(b, full, layout="gathered").items():
                out[i] = v
        return out


def record_reduce_levels(tag: str, levels: Sequence[dict]) -> None:
    """Mirror an accum plan's per-level reduce schedule into the unified
    collective registry: one record per (level, op) with the per-bucket
    bytes that actually move at that level."""
    for lv in levels:
        nbytes = [n for n in lv.get("bucket_nbytes", []) if n]
        record_collective(
            f"{tag}.grad.{lv['level']}.{lv['op']}", kind=lv["op"],
            plane="grad_reduce", axes=list(lv["axes"]), nbytes=nbytes)


def record_pipeline_edges(tag: str, *, stages: int, microbatches: int,
                          mb_nbytes: int, reverse: bool = False) -> None:
    """Register a pipeline schedule's ``ppermute`` ring edges: one
    microbatch buffer crosses a stage edge per tick (forward fill/drain;
    the 1F1B backward runs the mirrored reverse ring too)."""
    ticks = microbatches + stages - 1
    directions = 2 if reverse else 1
    record_collective(
        f"{tag}.ppermute", kind="ppermute", plane="pipeline", axes=[PIPE],
        nbytes=[mb_nbytes] * (ticks * directions), stages=stages,
        microbatches=microbatches, ticks_per_direction=ticks,
        directions=directions)


def moe_dispatch_ffn_combine(x: jax.Array, dispatch: jax.Array,
                             combine: jax.Array,
                             weights: Tuple[jax.Array, jax.Array, jax.Array],
                             mesh: Mesh, *, chunks: int = 2,
                             dtype: Any = jnp.bfloat16,
                             axis: str = EXPERT) -> jax.Array:
    """Expert-parallel SwiGLU dispatch → FFN → combine with the EP
    ``all_to_all`` issued explicitly per capacity chunk.

    Args:
      x: [B, T, D] tokens, batch dim sharded over the DP axes as usual.
      dispatch/combine: [B, T, E, C] routing tensors from
        :func:`tony_tpu.models.moe.router_assignment` (computed locally —
        no cross-device traffic).
      weights: stacked ``(w_gate, w_up, w_down)`` with leading expert dim
        E, sharded over ``axis``.
      chunks: capacity-chunk count — the capacity dim C splits into this
        many a2a+FFN waves so chunk *c+1*'s dispatch ``all_to_all`` rides
        under chunk *c*'s expert FFN compute (clamped to C).

    The math is the GSPMD dispatch-einsum path of
    :class:`~tony_tpu.models.moe.MoEMLP` with the same dtype casts; the
    only numerical difference is the per-chunk combine sum's fp
    reassociation. Owns ONLY the expert axis: model/seq/pipe mesh axes
    must be 1 (those belong to GSPMD, outside this region), and this must
    not be called inside another manual region (e.g. the accum engine's).
    """
    w_gate, w_up, w_down = weights
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis!r} axis: {mesh.axis_names}")
    ep = mesh.shape[axis]
    e = w_gate.shape[0]
    if e % ep:
        raise ValueError(
            f"n_experts={e} not divisible by the {ep}-way {axis!r} mesh "
            f"axis — every chip must own the same number of experts")
    for a in (MODEL, SEQ, PIPE):
        if a in mesh.axis_names and mesh.shape[a] > 1:
            raise ValueError(
                f"explicit a2a owns only the {axis!r} axis; mesh axis "
                f"{a!r} has size {mesh.shape[a]} — tensor/seq/pipe "
                f"sharding belongs to GSPMD (use the einsum path)")
    batch_axes = tuple(a for a in (SLICE, DATA, FSDP)
                       if a in mesh.axis_names and mesh.shape[a] > 1)
    dp = 1
    for a in batch_axes:
        dp *= mesh.shape[a]
    c = dispatch.shape[-1]
    n_chunks = max(1, min(chunks, c))
    bounds = np.cumsum([0] + [len(s) for s in
                              np.array_split(np.arange(c), n_chunks)])
    itemsize = np.dtype(dtype).itemsize
    # Per-issue PER-CHIP payload (the [E, B_local, Cc, D] tensor each
    # chip exchanges) — same semantics as the pipeline-edge records, so
    # collective_report() byte columns compare across planes.
    chunk_nbytes = [
        e * (x.shape[0] // dp) * int(bounds[j + 1] - bounds[j])
        * x.shape[-1] * itemsize for j in range(n_chunks)]
    record_collective("moe.dispatch", kind="all_to_all", plane="moe",
                      axes=[axis], nbytes=chunk_nbytes, chunks=n_chunks,
                      capacity=c, experts=e)
    record_collective("moe.combine", kind="all_to_all", plane="moe",
                      axes=[axis], nbytes=chunk_nbytes, chunks=n_chunks,
                      capacity=c, experts=e)

    x_spec = P(batch_axes or None)
    w_spec = P(axis)

    def spmd(x_l, disp_l, comb_l, wg_l, wu_l, wd_l):
        wg = wg_l.astype(dtype)
        wu = wu_l.astype(dtype)
        wd = wd_l.astype(dtype)
        y = jnp.zeros(x_l.shape[:2] + (x_l.shape[-1],), dtype)
        for j in range(n_chunks):
            c0, c1 = int(bounds[j]), int(bounds[j + 1])
            # Dispatch: local tokens → [E, B_l, Cc, D], then a2a exchanges
            # the expert dim for the group dim: each chip keeps its OWN
            # experts' slots from every peer's groups.
            xin = jnp.einsum("gsec,gsd->egcd",
                             disp_l[..., c0:c1].astype(dtype), x_l,
                             precision=jax.lax.Precision.DEFAULT)
            xin = jax.lax.all_to_all(xin, axis, split_axis=0,
                                     concat_axis=1, tiled=True)
            h = jax.nn.silu(jnp.einsum("egcd,edf->egcf", xin, wg))
            h = h * jnp.einsum("egcd,edf->egcf", xin, wu)
            out = jnp.einsum("egcf,efd->egcd", h, wd)
            # Combine a2a: the inverse exchange, back to token order.
            out = jax.lax.all_to_all(out, axis, split_axis=1,
                                     concat_axis=0, tiled=True)
            y = y + jnp.einsum("gsec,egcd->gsd",
                               comb_l[..., c0:c1].astype(dtype), out)
        return y

    return compat.shard_map(
        spmd, mesh,
        in_specs=(x_spec, x_spec, x_spec, w_spec, w_spec, w_spec),
        out_specs=x_spec)(x, dispatch, combine, w_gate, w_up, w_down)
