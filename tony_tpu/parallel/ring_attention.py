"""Ring attention: exact attention over sequence-sharded K/V (long context).

Absent from the reference (SURVEY.md §5.7 — TonY predates long-context
training and owns no tensor code); built here TPU-first per the task's
long-context requirement. The design is the standard blockwise-parallel ring
(Liu et al., "Ring Attention with Blockwise Transformers", arXiv:2310.01889,
public technique): each device holds one sequence shard of Q/K/V; K/V blocks
rotate around the ``seq`` mesh axis via ``jax.lax.ppermute`` (XLA lowers this
to ICI neighbor RDMA) while every device accumulates its Q-shard's attention
with an online-softmax running (max, normalizer, output) triple — so the
full T×T score matrix never materializes and communication overlaps compute
in steady state.

Math (fp32 accumulation regardless of input dtype): per incoming block
``s = q·kᵀ·scale``; ``m' = max(m, rowmax(s))``; ``p = exp(s − m')``;
``l ← l·exp(m−m') + rowsum(p)``; ``o ← o·exp(m−m') + p·v``; final ``o/l``.
Causal masking works on *global* positions: Q shard ``r`` attends K shard
``j`` fully when ``j < r``, causally when ``j == r``, not at all when
``j > r`` (those steps contribute zeros via the mask).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tony_tpu import compat

_NEG_INF = -1e30


def _block_step(q, k, v, m, l, o, scale, mask):
    """One online-softmax accumulation step. q:[B,H,Tq,D] k/v:[B,H,Tk,D]
    mask:[Tq,Tk] bool (True = attend); m,l:[B,H,Tq,1] o:[B,H,Tq,D], all f32."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    s = jnp.where(mask[None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    # exp(-1e30 - m) underflows to 0, so fully-masked rows stay all-zero.
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    o_new = o * alpha + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m_new, l_new, o_new


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str, causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Exact attention where K/V are sharded along ``axis_name``; call inside
    ``shard_map``/``pmap`` with per-device shards.

    Shapes (per device): q ``[batch, heads, seq_shard, head_dim]``; k/v
    may carry FEWER heads (GQA, ``heads % kv_heads == 0``) — query head h
    reads kv head ``h·kv/heads`` and, crucially, the blocks that rotate
    around the ring stay at the NARROW width, so GQA divides the ICI
    traffic by the group size instead of shipping repeated phantom heads.
    Returns ``[batch, heads, seq_shard, head_dim]`` in ``q.dtype``.
    """
    sp = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, tq, d = q.shape
    hkv, tk = k.shape[1], k.shape[2]
    if h % hkv:
        raise ValueError(f"query heads {h} not a multiple of kv heads {hkv}")
    reps = h // hkv
    if scale is None:
        scale = d ** -0.5

    # Zero-copy GQA: fold the group of query heads a kv head serves into
    # the q sequence dim — [b, hkv, reps·tq, d] against [b, hkv, tk, d] is
    # one einsum with K/V broadcast over the group, no jnp.repeat. Row
    # r·tq+qi keeps query position qi, so the causal mask just tiles.
    qr = q.reshape(b, hkv, reps * tq, d)
    q_pos = my * tq + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
    k_iota = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)

    m0 = jnp.full((b, hkv, reps * tq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, reps * tq, 1), jnp.float32)
    o0 = jnp.zeros((b, hkv, reps * tq, d), jnp.float32)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(carry, step_idx):
        k_blk, v_blk, m, l, o = carry
        j = (my - step_idx) % sp                    # whose shard we hold now
        if causal:
            mask = q_pos >= (j * tk + k_iota)
        else:
            mask = jnp.ones((tq, tk), bool)
        mask = jnp.tile(mask, (reps, 1)) if reps > 1 else mask
        m, l, o = _block_step(qr, k_blk, v_blk, m, l, o, scale, mask)
        # Rotate K/V around the ring (skip after the last accumulation).
        k_nxt, v_nxt = jax.lax.cond(
            step_idx < sp - 1,
            lambda: (jax.lax.ppermute(k_blk, axis_name, perm),
                     jax.lax.ppermute(v_blk, axis_name, perm)),
            lambda: (k_blk, v_blk))
        return (k_nxt, v_nxt, m, l, o), None

    (_, _, m, l, o), _ = jax.lax.scan(
        step, (k, v, m0, l0, o0), jnp.arange(sp))
    out = jnp.where(l > 0, o / jnp.where(l > 0, l, 1.0), 0.0)
    return out.reshape(b, h, tq, d).astype(q.dtype)


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                           mesh: Mesh, causal: bool = True,
                           seq_axis: str = "seq",
                           model_axis: Optional[str] = "model") -> jax.Array:
    """Global-array entry point: shard_maps :func:`ring_attention` over the
    mesh. q/k/v are logically-global ``[batch, heads, seq, head_dim]``; the
    seq dim is sharded over ``seq_axis`` and heads over ``model_axis``."""
    from tony_tpu.parallel.overlap import sync_axes  # call-time: no cycle

    dp_axes = sync_axes(mesh)
    tp = mesh.shape.get(model_axis, 1) if model_axis else 1
    if tp > 1 and k.shape[1] % tp:
        # GQA heads must divide the tensor-parallel axis to stay narrow;
        # when they don't (e.g. kv=2 over tp=4), repeat K/V up to the
        # query head count — correct, just without the narrow-ring ICI
        # saving (which is unexpressible for this sharding anyway).
        reps = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, reps, axis=1)
        v = jnp.repeat(v, reps, axis=1)
    spec = P(dp_axes or None, model_axis, seq_axis, None)
    fn = functools.partial(ring_attention, axis_name=seq_axis, causal=causal)
    return compat.shard_map(
        fn, mesh, in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)
