"""Comm/compute overlap engine: bucketed gradient sync under microbatched
accumulation, plus the XLA scheduler knobs that make the overlap real.

The seed's train step reduces gradients in one monolithic GSPMD ``psum``
issued after the full backward — zero overlap structure, the exact thing
Horovod's bucketed allreduce (arXiv:1802.05799) fixed for GPU rings and T3
(arXiv:2401.16677) shows is where modern MFU headroom lives. This module
builds that layer natively:

* :class:`GradBuckets` — a Horovod-style byte-threshold bucketing plan over
  the flattened grad pytree. Each bucket concatenates same-dtype leaves up
  to ``bucket_bytes`` and is reduced as ONE collective, so small tensors
  amortize launch latency and big ones don't serialize the whole sync.
* :func:`microbatch_grads` — the accumulation step core: the local batch is
  split into K microbatches inside one ``lax.scan``; each microbatch's
  grads are packed and reduced per bucket (``psum`` or
  ``psum_scatter``+``all_gather``) *inside* the scan body, so under XLA's
  latency-hiding scheduler the reduction of microbatch *i*'s buckets
  overlaps the backward compute of microbatch *i+1*.
  :func:`tony_tpu.train.make_accum_train_step` wraps this into a drop-in
  train step.
* :func:`overlap_xla_flags` — the latency-hiding-scheduler / async
  collective flags, merged into an ``XLA_FLAGS`` string with user-set
  values winning; :class:`tony_tpu.runtime.jax_runtime.JAXTaskAdapter`
  injects the result so tony-submitted jobs get the overlap for free.

Scope: the engine treats the ``data`` and ``fsdp`` mesh axes as the
gradient-sync group with params replicated inside the manually-sharded
region (pure DP semantics — the layout ``batch_sharding`` feeds). Sharded-
param (ZeRO-3) accumulation and cross-slice DCN bucketing are ROADMAP
follow-ons built on this layer.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tony_tpu import compat
from tony_tpu.parallel import DATA, FSDP

# Horovod's fusion buffer defaults to 64 MiB for NCCL rings; ICI collectives
# saturate earlier, and smaller buckets mean the first reduction launches
# sooner after the first grads materialize. 4 MiB is the planner default;
# callers tune per model via ``bucket_bytes``.
DEFAULT_BUCKET_BYTES = 4 << 20

# The scheduler knobs (MaxText/XLA-team standard set): latency-hiding
# scheduling so async collective pairs slide over compute, plus async
# collective fusion so the per-bucket reduces actually become async pairs.
# TPU-namespaced flags ONLY: XLA ABORTS the process on any flag its build
# doesn't know (measured on the CPU wheel), so this set must never reach a
# non-TPU jaxlib — the runtime injects it only for TPU-resourced tasks.
OVERLAP_XLA_FLAGS: Tuple[str, ...] = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
)


def _flag_name(flag: str) -> str:
    return flag.lstrip("-").split("=", 1)[0]


def overlap_xla_flags(existing: str = "") -> str:
    """Merge :data:`OVERLAP_XLA_FLAGS` into an ``XLA_FLAGS`` string.

    A flag the caller already set (any value) is kept and ours dropped —
    injection must never override an operator's explicit tuning.
    """
    present = {_flag_name(f) for f in existing.split() if f.startswith("-")}
    merged = [f for f in OVERLAP_XLA_FLAGS if _flag_name(f) not in present]
    return " ".join(filter(None, [existing.strip(), *merged])).strip()


def sync_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The gradient-sync mesh axes: both DP axes, in mesh order — matches
    :func:`tony_tpu.parallel.batch_sharding`'s batch placement."""
    return tuple(a for a in (DATA, FSDP) if a in mesh.axis_names)


def sync_size(mesh: Mesh) -> int:
    """Device count of the gradient-sync group (product of the DP axes) —
    the denominator shared by the accum step and the pipeline schedules."""
    size = 1
    for a in sync_axes(mesh):
        size *= mesh.shape[a]
    return size


@dataclass(frozen=True)
class GradBuckets:
    """A size-targeted partition of a grad pytree's leaves into reduction
    buckets: every leaf lands in exactly one bucket; leaves of one dtype
    pack together (a bucket is one concatenated 1-D buffer) in flatten
    order until adding the next leaf would cross ``threshold`` bytes; a
    single leaf bigger than the threshold gets a bucket of its own."""

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    buckets: Tuple[Tuple[int, ...], ...]   # leaf indices per bucket
    bucket_nbytes: Tuple[int, ...]         # payload bytes per bucket
    bucket_numel: Tuple[int, ...]          # payload elements per bucket
    threshold: int

    @classmethod
    def plan(cls, tree: Any,
             bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> "GradBuckets":
        """Plan from any pytree of arrays / ShapeDtypeStructs / tracers
        (only ``.shape``/``.dtype`` are read — works under ``eval_shape``
        and inside a jit trace)."""
        if bucket_bytes <= 0:
            raise ValueError(f"bucket_bytes must be positive, got "
                             f"{bucket_bytes}")
        leaves, treedef = jax.tree.flatten(tree)
        shapes = tuple(tuple(l.shape) for l in leaves)
        dtypes = tuple(np.dtype(l.dtype) for l in leaves)
        sizes = [int(np.prod(s, dtype=np.int64)) * d.itemsize
                 for s, d in zip(shapes, dtypes)]
        by_dtype: Dict[Any, list] = {}
        for i, d in enumerate(dtypes):
            by_dtype.setdefault(d, []).append(i)
        buckets, nbytes, numel = [], [], []

        def close(cur, cur_b, d):
            buckets.append(tuple(cur))
            nbytes.append(cur_b)
            numel.append(cur_b // d.itemsize)

        for d, idxs in by_dtype.items():
            cur: list = []
            cur_b = 0
            for i in idxs:
                if cur and cur_b + sizes[i] > bucket_bytes:
                    close(cur, cur_b, d)
                    cur, cur_b = [], 0
                cur.append(i)
                cur_b += sizes[i]
            if cur:
                close(cur, cur_b, d)
        return cls(treedef, shapes, dtypes, tuple(buckets), tuple(nbytes),
                   tuple(numel), bucket_bytes)

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)

    def pack(self, tree: Any) -> list:
        """Pytree → per-bucket 1-D concatenated buffers."""
        leaves = jax.tree.leaves(tree)
        return [jnp.concatenate([leaves[i].reshape(-1) for i in idxs])
                if len(idxs) > 1 else leaves[idxs[0]].reshape(-1)
                for idxs in self.buckets]

    def unpack(self, bufs: Sequence[jax.Array]) -> Any:
        """Per-bucket buffers → pytree (inverse of :meth:`pack`)."""
        leaves: list = [None] * len(self.shapes)
        for buf, idxs in zip(bufs, self.buckets):
            off = 0
            for i in idxs:
                n = int(np.prod(self.shapes[i], dtype=np.int64))
                leaves[i] = jax.lax.dynamic_slice_in_dim(
                    buf, off, n).reshape(self.shapes[i])
                off += n
        return jax.tree.unflatten(self.treedef, leaves)

    def reduce(self, tree: Any, axis_names: Tuple[str, ...], *,
               op: str = "all_reduce", group_size: int = 1) -> Any:
        """Explicit per-bucket cross-replica sum of ``tree`` (must be
        called inside a manually-sharded region over ``axis_names``).

        ``op="all_reduce"``: one ``psum`` per bucket.
        ``op="reduce_scatter"``: ``psum_scatter`` per (padded) bucket +
        one tail ``all_gather`` — the bandwidth-optimal RS+AG split of an
        allreduce; ``group_size`` must be the product of the axis sizes.
        """
        bufs = self.pack(tree)
        if op == "all_reduce":
            return self.unpack([jax.lax.psum(b, axis_names) for b in bufs])
        if op != "reduce_scatter":
            raise ValueError(f"unknown reduce op {op!r} "
                             "(all_reduce|reduce_scatter)")
        out = []
        for b in bufs:
            n = b.shape[0]
            pad = (-n) % group_size
            if pad:
                b = jnp.concatenate([b, jnp.zeros((pad,), b.dtype)])
            shard = jax.lax.psum_scatter(b, axis_names, tiled=True)
            full = jax.lax.all_gather(shard, axis_names, tiled=True)
            out.append(full[:n] if pad else full)
        return self.unpack(out)


def _record(tag: str, **fields) -> None:
    # Trace-time side channel into the profiler registry (lazy import:
    # parallel must stay importable without the profiler stack).
    try:
        from tony_tpu import profiler
        profiler.record_overlap(tag, **fields)
    except Exception:   # noqa: BLE001 — bookkeeping must never sink a step
        pass


def microbatch_grads(loss_fn: Callable[[Any, Any], Any], params: Any,
                     batch: Any, mesh: Mesh, *, microbatches: int,
                     buckets: Optional[GradBuckets] = None,
                     bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                     reduce_op: str = "all_reduce",
                     has_aux: bool = False):
    """Gradient accumulation over ``microbatches`` with per-bucket sync.

    ``loss_fn(params, microbatch) -> loss`` (or ``(loss, aux)`` with
    ``has_aux``) is the per-shard loss — a *mean* over its microbatch
    slice, collective-free (the engine owns all cross-device traffic, like
    ``gpipe``'s ``stage_fn`` contract). Params are replicated across the
    sync axes inside the region; the batch's leading dim is split over
    them. Returns ``(loss, grads)`` (or ``(loss, aux, grads)``): the
    global-mean loss and grads, replicated — numerically the monolithic
    full-batch step up to fp reassociation.

    Inside the scan body each microbatch's grads are reduced bucket by
    bucket, so the collective for microbatch *i* is in flight while
    microbatch *i+1*'s forward/backward computes (the Horovod overlap,
    expressed for XLA's latency-hiding scheduler — see
    :func:`overlap_xla_flags`).
    """
    axes = sync_axes(mesh)
    group = sync_size(mesh)
    lead = jax.tree.leaves(batch)[0].shape[0]
    if lead % (group * microbatches):
        raise ValueError(
            f"global batch {lead} not divisible by sync group {group} x "
            f"microbatches {microbatches} (= {group * microbatches})")
    plan = buckets if buckets is not None else GradBuckets.plan(
        params, bucket_bytes)
    _record("accum_step", n_buckets=plan.n_buckets,
            bucket_nbytes=list(plan.bucket_nbytes),
            threshold=plan.threshold, microbatches=microbatches,
            reduce_op=reduce_op, sync_group=group)
    p_specs = jax.tree.map(lambda _: P(), params)
    b_specs = jax.tree.map(lambda _: P(axes), batch)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=has_aux)

    def spmd(params, local):
        mbs = jax.tree.map(
            lambda x: x.reshape((microbatches, x.shape[0] // microbatches)
                                + x.shape[1:]), local)
        acc0 = []
        for idxs, n in zip(plan.buckets, plan.bucket_numel):
            dt = plan.dtypes[idxs[0]]
            if reduce_op == "reduce_scatter":
                n = (n + ((-n) % group)) // group   # padded local shard
            acc0.append(jnp.zeros((n,), dt))

        def body(carry, mb):
            loss_acc, aux_acc, acc = carry
            out, grads = grad_fn(params, mb)
            loss, aux = out if has_aux else (out, jnp.float32(0.0))
            bufs = plan.pack(grads)
            nxt = []
            for a, b in zip(acc, bufs):
                if reduce_op == "reduce_scatter":
                    pad = (-b.shape[0]) % group
                    if pad:
                        b = jnp.concatenate(
                            [b, jnp.zeros((pad,), b.dtype)])
                    nxt.append(a + jax.lax.psum_scatter(b, axes,
                                                        tiled=True))
                else:
                    nxt.append(a + jax.lax.psum(b, axes))
            return (loss_acc + loss, aux_acc + aux, nxt), None

        (loss, aux, acc), _ = jax.lax.scan(
            body, (jnp.float32(0.0), jnp.float32(0.0), acc0), mbs)
        if reduce_op == "reduce_scatter":
            acc = [jax.lax.all_gather(a, axes, tiled=True)[:n]
                   for a, n in zip(acc, plan.bucket_numel)]
        denom = microbatches * group
        grads = jax.tree.map(lambda b: b / denom, plan.unpack(acc))
        loss = jax.lax.psum(loss, axes) / denom
        aux = jax.lax.psum(aux, axes) / denom
        return loss, aux, grads

    loss, aux, grads = compat.shard_map(
        spmd, mesh, in_specs=(p_specs, b_specs),
        out_specs=(P(), P(), p_specs))(params, batch)
    if has_aux:
        return loss, aux, grads
    return loss, grads
