"""Comm/compute overlap engine: bucketed gradient sync under microbatched
accumulation, plus the XLA scheduler knobs that make the overlap real.

The seed's train step reduces gradients in one monolithic GSPMD ``psum``
issued after the full backward — zero overlap structure, the exact thing
Horovod's bucketed allreduce (arXiv:1802.05799) fixed for GPU rings and T3
(arXiv:2401.16677) shows is where modern MFU headroom lives. This module
builds that layer natively:

* :class:`GradBuckets` — a Horovod-style byte-threshold bucketing plan over
  the flattened grad pytree. Each bucket concatenates same-dtype leaves up
  to ``bucket_bytes`` and is reduced as ONE collective, so small tensors
  amortize launch latency and big ones don't serialize the whole sync.
  :meth:`GradBuckets.plan_sharded` is the ZeRO-3 planner: leaves with an
  fsdp-sharded dim are packed *shard-major*, so one ``psum_scatter`` over
  the fsdp axis lands each microbatch's grads straight in the shard layout
  — no gather, no replicated-grad materialization.
* :func:`microbatch_grads` — the accumulation step core: the local batch is
  split into K microbatches inside one ``lax.scan``; each microbatch's
  grads are packed and reduced per bucket *inside* the scan body, so under
  XLA's latency-hiding scheduler the reduction of microbatch *i*'s buckets
  overlaps the backward compute of microbatch *i+1*. On a multi-slice mesh
  the reduce is two-level: ``psum_scatter`` intra-slice over ICI per
  bucket, then a per-bucket allreduce over the DCN ``slice`` axis issued
  inside the scan — the slow cross-slice hop rides under both the next
  microbatch's backward and the next bucket's ICI phase.
  :func:`tony_tpu.train.make_accum_train_step` wraps this into a drop-in
  train step and auto-detects the ZeRO-3 layout from the state's
  shardings.
* :func:`overlap_xla_flags` — the latency-hiding-scheduler / async
  collective flags (plus the DCN set for multi-slice jobs), merged into an
  ``XLA_FLAGS`` string with user-set values winning;
  :class:`tony_tpu.runtime.jax_runtime.JAXTaskAdapter` injects the result
  so tony-submitted jobs get the overlap for free.
"""

from __future__ import annotations

import functools
import logging
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tony_tpu import compat
from tony_tpu._trace import trace_record
from tony_tpu.parallel import DATA, FSDP, SLICE

_log = logging.getLogger(__name__)

# Horovod's fusion buffer defaults to 64 MiB for NCCL rings; ICI collectives
# saturate earlier, and smaller buckets mean the first reduction launches
# sooner after the first grads materialize. 4 MiB is the planner default;
# callers tune per model via ``bucket_bytes``.
DEFAULT_BUCKET_BYTES = 4 << 20

# The scheduler knobs (MaxText/XLA-team standard set): latency-hiding
# scheduling so async collective pairs slide over compute, plus async
# collective fusion so the per-bucket reduces actually become async pairs.
# TPU-namespaced flags ONLY: XLA ABORTS the process on any flag its build
# doesn't know (measured on the CPU wheel), so this set must never reach a
# non-TPU jaxlib — the runtime injects it only for TPU-resourced tasks.
OVERLAP_XLA_FLAGS: Tuple[str, ...] = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
)

# Multi-slice additions: let the scheduler split/overlap the DCN allreduces
# that the hierarchical reduce issues per bucket (different-sized DCN ops
# must not serialize behind each other). Same TPU-namespace-only rule.
MULTISLICE_XLA_FLAGS: Tuple[str, ...] = (
    "--xla_tpu_enable_data_parallel_all_reduce_opt=true",
    "--xla_tpu_data_parallel_opt_different_sized_ops=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_reduce=true",
)


def _flag_name(flag: str) -> str:
    return flag.lstrip("-").split("=", 1)[0]


def overlap_xla_flags(existing: str = "", *, multislice: bool = False) -> str:
    """Merge :data:`OVERLAP_XLA_FLAGS` (and, for multi-slice jobs,
    :data:`MULTISLICE_XLA_FLAGS`) into an ``XLA_FLAGS`` string.

    A flag the caller already set (any value) is kept and ours dropped —
    injection must never override an operator's explicit tuning.
    """
    ours = OVERLAP_XLA_FLAGS + (MULTISLICE_XLA_FLAGS if multislice else ())
    present = {_flag_name(f) for f in existing.split() if f.startswith("-")}
    merged = [f for f in ours if _flag_name(f) not in present]
    return " ".join(filter(None, [existing.strip(), *merged])).strip()


def sync_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The gradient-sync mesh axes: the DCN slice axis plus both DP axes,
    in mesh order — matches :func:`tony_tpu.parallel.batch_sharding`'s
    batch placement."""
    return tuple(a for a in (SLICE, DATA, FSDP) if a in mesh.axis_names)


def sync_size(mesh: Mesh) -> int:
    """Device count of the gradient-sync group (product of the slice and DP
    axes) — the denominator shared by the accum step and the pipeline
    schedules."""
    size = 1
    for a in sync_axes(mesh):
        size *= mesh.shape[a]
    return size


def ici_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The intra-slice (ICI) gradient-sync axes: :func:`sync_axes` minus
    the DCN slice axis."""
    return tuple(a for a in (DATA, FSDP) if a in mesh.axis_names)


def dcn_axis(mesh: Mesh) -> Optional[str]:
    """The cross-slice (DCN) sync axis, or None on a single-slice mesh —
    hierarchical reduction only exists when this is set."""
    if SLICE in mesh.axis_names and mesh.shape[SLICE] > 1:
        return SLICE
    return None


def fsdp_param_specs(params: Any, mesh: Mesh) -> Optional[Any]:
    """Detect a ZeRO-3 (fsdp-sharded) parameter layout from the arrays'
    committed shardings: a pytree of :class:`PartitionSpec` (one per leaf,
    ``P()`` for replicated leaves) when at least one leaf is sharded over
    the fsdp axis of a mesh with ``fsdp > 1``, else ``None``.

    This is how ``train.make_accum_train_step`` decides between the
    replicated-param and sharded-param accumulation paths without a flag:
    the layout the state was created with IS the contract.
    """
    if FSDP not in mesh.axis_names or mesh.shape[FSDP] <= 1:
        return None
    leaves, treedef = jax.tree.flatten(params)
    specs: List[P] = []
    found = False
    for leaf in leaves:
        spec = getattr(getattr(leaf, "sharding", None), "spec", None)
        if spec is None:
            spec = P()
        # Strip size-1 mesh axes (a spec naming "model" on a model=1 mesh
        # is replicated in fact): the engine plans off REAL sharding.
        entries = []
        for entry in tuple(spec):
            names = entry if isinstance(entry, tuple) else (
                (entry,) if entry is not None else ())
            kept = tuple(a for a in names
                         if a in mesh.axis_names and mesh.shape[a] > 1)
            if FSDP in kept:
                found = True
            entries.append(kept if len(kept) > 1
                           else (kept[0] if kept else None))
        specs.append(P(*entries))
    if not found:
        return None
    return jax.tree.unflatten(treedef, specs)


def _shard_dim(spec: Any, shape: Tuple[int, ...], shard_axis: str,
               shard_size: int) -> Optional[int]:
    """The leaf dim sharded over ``shard_axis`` per ``spec`` (None when
    replicated). Raises on layouts the accum engine cannot own: sharding
    over any other mesh axis, or fsdp combined with another axis on one
    dim. A sharded dim NOT divisible by the shard count is legal — the
    planner pads it into its scatter bucket (see ``shard_pads``)."""
    dim: Optional[int] = None
    for d, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        if shard_axis in names:
            if len(names) > 1:
                raise ValueError(
                    f"param dim {d} sharded over {names}: the accum engine "
                    f"supports {shard_axis!r} alone on a dim")
            if dim is not None:
                raise ValueError(
                    f"param sharded over {shard_axis!r} on two dims "
                    f"({dim} and {d}) — not a ZeRO-3 layout")
            dim = d
        else:
            raise ValueError(
                f"param dim {d} sharded over {names}: only {shard_axis!r} "
                f"is supported inside the accum engine (model/pipe/seq "
                f"axes belong to GSPMD, not the manual region)")
    return dim


@dataclass(frozen=True)
class GradBuckets:
    """A size-targeted partition of a grad pytree's leaves into reduction
    buckets: every leaf lands in exactly one bucket; leaves of one dtype
    pack together (a bucket is one concatenated 1-D buffer) in flatten
    order until adding the next leaf would cross ``threshold`` bytes; a
    single leaf bigger than the threshold gets a bucket of its own.

    A plan from :meth:`plan_sharded` additionally carries the ZeRO-3 shard
    layout: ``shard_dims[i]`` is leaf *i*'s fsdp-sharded dim (None for
    replicated leaves), and scatter buckets (``bucket_scatter``) hold only
    sharded leaves, packed shard-major — chunk *f* of the buffer is the
    concatenation of every member leaf's shard *f* — so ``psum_scatter``
    over the fsdp axis yields exactly the local shard of the summed grads.

    Leaves whose sharded dim does NOT divide the fsdp axis (the uneven
    ZeRO-3 follow-on) are padded into dedicated scatter buckets
    (``shard_pads[i]`` rows of zeros on the shard dim, ``bucket_padded``
    marks the buckets): the in-scan ``psum_scatter`` is identical, and the
    consumer re-gathers + unpads them after the scan (their grads come
    back whole — the uneven leaf can't live in the shard layout).
    """

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    buckets: Tuple[Tuple[int, ...], ...]   # leaf indices per bucket
    bucket_nbytes: Tuple[int, ...]         # payload bytes per bucket
    bucket_numel: Tuple[int, ...]          # payload elements per bucket
    threshold: int
    shard_size: int = 1                    # fsdp axis size (1 = replicated)
    shard_dims: Tuple[Optional[int], ...] = ()    # per-leaf sharded dim
    bucket_scatter: Tuple[bool, ...] = ()         # per-bucket scatter flag
    shard_pads: Tuple[int, ...] = ()       # per-leaf pad rows on shard dim
    bucket_padded: Tuple[bool, ...] = ()   # per-bucket uneven-leaf flag

    @classmethod
    def plan(cls, tree: Any,
             bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> "GradBuckets":
        """Plan from any pytree of arrays / ShapeDtypeStructs / tracers
        (only ``.shape``/``.dtype`` are read — works under ``eval_shape``
        and inside a jit trace)."""
        return cls._plan(tree, bucket_bytes, shard_dims=None, shard_size=1)

    @classmethod
    def plan_sharded(cls, tree: Any, specs: Any, *, shard_size: int,
                     bucket_bytes: int = DEFAULT_BUCKET_BYTES
                     ) -> "GradBuckets":
        """ZeRO-3 plan: ``specs`` is a pytree of :class:`PartitionSpec`
        matching ``tree`` (``P()`` = replicated leaf); leaves with an
        fsdp-sharded dim land in scatter buckets (uneven dims padded into
        their own buckets), the rest in ordinary allreduce buckets.
        ``shard_size`` is the fsdp axis size."""
        leaves = jax.tree.leaves(tree)
        spec_leaves = jax.tree.leaves(
            specs, is_leaf=lambda x: isinstance(x, P))
        if len(spec_leaves) != len(leaves):
            raise ValueError(
                f"param/spec trees disagree: {len(leaves)} leaves vs "
                f"{len(spec_leaves)} specs")
        shard_dims = tuple(
            _shard_dim(s, tuple(l.shape), FSDP, shard_size)
            for l, s in zip(leaves, spec_leaves))
        return cls._plan(tree, bucket_bytes, shard_dims=shard_dims,
                         shard_size=shard_size)

    @classmethod
    def _plan(cls, tree, bucket_bytes, *, shard_dims, shard_size):
        if bucket_bytes <= 0:
            raise ValueError(f"bucket_bytes must be positive, got "
                             f"{bucket_bytes}")
        leaves, treedef = jax.tree.flatten(tree)
        if not leaves:
            raise ValueError(
                "GradBuckets.plan: empty gradient pytree — nothing to "
                "bucket (did the loss close over its params instead of "
                "taking them as an argument?)")
        shapes = tuple(tuple(l.shape) for l in leaves)
        dtypes = tuple(np.dtype(l.dtype) for l in leaves)
        if shard_dims is None:
            shard_dims = (None,) * len(leaves)
        pads = tuple(
            (-shapes[i][d]) % shard_size if (d := shard_dims[i]) is not None
            and shard_size > 1 else 0
            for i in range(len(leaves)))
        # Payload size: scatter leaves count their PADDED extent — the pad
        # rows ride the collective, so the planner must budget them.
        sizes = []
        for i, (s, d) in enumerate(zip(shapes, dtypes)):
            numel = int(np.prod(s, dtype=np.int64))
            if pads[i] and s[shard_dims[i]]:
                numel = numel // s[shard_dims[i]] * (s[shard_dims[i]]
                                                    + pads[i])
            sizes.append(numel * d.itemsize)
        # Group key: (dtype, scatterable, padded) — a bucket is one
        # collective; a psum_scatter bucket cannot host replicated leaves
        # (their grads must come back whole, not as a shard), and padded
        # (uneven) leaves get their own buckets because theirs are
        # re-gathered after the scan while even leaves stay sharded.
        groups: Dict[Tuple[Any, bool, bool], list] = {}
        for i, d in enumerate(dtypes):
            sc = shard_dims[i] is not None and shard_size > 1
            groups.setdefault((d, sc, sc and pads[i] > 0), []).append(i)
        buckets, nbytes, numel, scatter, padded = [], [], [], [], []

        def close(cur, cur_b, d, sc, pd):
            buckets.append(tuple(cur))
            nbytes.append(cur_b)
            numel.append(cur_b // d.itemsize)
            scatter.append(sc)
            padded.append(pd)

        for (d, sc, pd), idxs in groups.items():
            cur: list = []
            cur_b = 0
            for i in idxs:
                if cur and cur_b + sizes[i] > bucket_bytes:
                    close(cur, cur_b, d, sc, pd)
                    cur, cur_b = [], 0
                cur.append(i)
                cur_b += sizes[i]
            if cur:
                close(cur, cur_b, d, sc, pd)
        return cls(treedef, shapes, dtypes, tuple(buckets), tuple(nbytes),
                   tuple(numel), bucket_bytes, shard_size, shard_dims,
                   tuple(scatter), pads, tuple(padded))

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)

    @property
    def n_scatter_buckets(self) -> int:
        return sum(1 for s in self.bucket_scatter if s)

    def _is_scatter(self, b: int) -> bool:
        return bool(self.bucket_scatter) and self.bucket_scatter[b]

    def _is_padded(self, b: int) -> bool:
        return bool(self.bucket_padded) and self.bucket_padded[b]

    def _pad(self, i: int) -> int:
        return self.shard_pads[i] if self.shard_pads else 0

    def padded_shape(self, i: int) -> Tuple[int, ...]:
        """Leaf *i*'s shape with the uneven-shard pad applied."""
        pad = self._pad(i)
        if not pad:
            return self.shapes[i]
        s = list(self.shapes[i])
        s[self.shard_dims[i]] += pad
        return tuple(s)

    def shard_shape(self, i: int) -> Tuple[int, ...]:
        """Leaf *i*'s local-shard shape under the plan's fsdp layout
        (padded extent for uneven leaves — their shard IS padded)."""
        d = self.shard_dims[i] if self.shard_dims else None
        if d is None or self.shard_size == 1:
            return self.shapes[i]
        s = list(self.padded_shape(i))
        s[d] //= self.shard_size
        return tuple(s)

    def pack(self, tree: Any) -> list:
        """Pytree → per-bucket 1-D concatenated buffers. Scatter buckets
        are packed shard-major (chunk f = every member leaf's shard f), so
        a ``psum_scatter`` over the fsdp axis returns the local shard;
        uneven leaves are zero-padded on the shard dim first."""
        leaves = jax.tree.leaves(tree)
        out = []
        for b, idxs in enumerate(self.buckets):
            if self._is_scatter(b):
                src = {}
                for i in idxs:
                    pad = self._pad(i)
                    if pad:
                        d = self.shard_dims[i]
                        widths = [(0, pad if k == d else 0)
                                  for k in range(len(self.shapes[i]))]
                        src[i] = jnp.pad(leaves[i], widths)
                    else:
                        src[i] = leaves[i]
                parts = []
                for f in range(self.shard_size):
                    for i in idxs:
                        d = self.shard_dims[i]
                        n = self.padded_shape(i)[d] // self.shard_size
                        parts.append(jax.lax.slice_in_dim(
                            src[i], f * n, (f + 1) * n,
                            axis=d).reshape(-1))
                out.append(jnp.concatenate(parts))
            elif len(idxs) > 1:
                out.append(jnp.concatenate(
                    [leaves[i].reshape(-1) for i in idxs]))
            else:
                out.append(leaves[idxs[0]].reshape(-1))
        return out

    def leaf_buffers(self, b: int, buf: jax.Array, *,
                     layout: str) -> Dict[int, jax.Array]:
        """Bucket *b*'s buffer → ``{leaf_index: array}``.

        ``layout="full"``: linear packing of whole leaves (allreduce / re-
        gathered rs buckets). ``layout="shard"``: a scatter bucket's local
        ``psum_scatter`` chunk → shard-shaped leaves. ``layout="gathered"``:
        a scatter bucket's buffer re-gathered over the fsdp axis (shard-
        major, padded) → whole UNPADDED leaves — the uneven-leaf exit path.
        """
        idxs = self.buckets[b]
        out: Dict[int, jax.Array] = {}
        if layout == "gathered":
            chunk = self.bucket_numel[b] // self.shard_size
            off = 0
            for i in idxs:
                shp = self.shard_shape(i)
                n = int(np.prod(shp, dtype=np.int64))
                d = self.shard_dims[i]
                full = jnp.concatenate(
                    [jax.lax.dynamic_slice_in_dim(
                        buf, f * chunk + off, n).reshape(shp)
                     for f in range(self.shard_size)], axis=d)
                if self._pad(i):
                    full = jax.lax.slice_in_dim(
                        full, 0, self.shapes[i][d], axis=d)
                out[i] = full
                off += n
            return out
        if layout not in ("full", "shard"):
            raise ValueError(f"unknown layout {layout!r}")
        off = 0
        for i in idxs:
            shp = self.shard_shape(i) if layout == "shard" \
                else self.shapes[i]
            n = int(np.prod(shp, dtype=np.int64))
            out[i] = jax.lax.dynamic_slice_in_dim(
                buf, off, n).reshape(shp)
            off += n
        return out

    def unpack(self, bufs: Sequence[jax.Array]) -> Any:
        """Per-bucket FULL buffers → pytree (inverse of :meth:`pack` for
        non-scatter plans / gathered buffers)."""
        leaves: list = [None] * len(self.shapes)
        for b in range(len(self.buckets)):
            for i, v in self.leaf_buffers(b, bufs[b], layout="full").items():
                leaves[i] = v
        return jax.tree.unflatten(self.treedef, leaves)

    def unpack_shards(self, bufs: Sequence[jax.Array]) -> Any:
        """Per-bucket buffers → pytree in the SHARD layout: scatter
        buckets' buffers are the local ``psum_scatter`` chunk and unpack to
        shard-shaped leaves; other buffers unpack whole."""
        leaves: list = [None] * len(self.shapes)
        for b in range(len(self.buckets)):
            layout = "shard" if self._is_scatter(b) else "full"
            for i, v in self.leaf_buffers(b, bufs[b],
                                          layout=layout).items():
                leaves[i] = v
        return jax.tree.unflatten(self.treedef, leaves)

    def reduce(self, tree: Any, axis_names: Tuple[str, ...], *,
               op: str = "all_reduce", group_size: int = 1) -> Any:
        """Explicit per-bucket cross-replica sum of ``tree`` (must be
        called inside a manually-sharded region over ``axis_names``).

        ``op="all_reduce"``: one ``psum`` per bucket.
        ``op="reduce_scatter"``: ``psum_scatter`` per (padded) bucket +
        one tail ``all_gather`` — the bandwidth-optimal RS+AG split of an
        allreduce; ``group_size`` must be the product of the axis sizes.
        """
        if self.n_scatter_buckets:
            raise ValueError(
                "reduce() is the replicated-plan primitive; ZeRO-3 "
                "scatter plans are driven by microbatch_grads (shard-"
                "major buffers unpack to the SHARD layout, not whole "
                "leaves)")
        bufs = self.pack(tree)
        if op == "all_reduce":
            return self.unpack([jax.lax.psum(b, axis_names) for b in bufs])
        if op != "reduce_scatter":
            raise ValueError(f"unknown reduce op {op!r} "
                             "(all_reduce|reduce_scatter)")
        out = []
        for b in bufs:
            n = b.shape[0]
            pad = (-n) % group_size
            if pad:
                b = jnp.concatenate([b, jnp.zeros((pad,), b.dtype)])
            shard = jax.lax.psum_scatter(b, axis_names, tiled=True)
            full = jax.lax.all_gather(shard, axis_names, tiled=True)
            out.append(full[:n] if pad else full)
        return self.unpack(out)


# Trace-time side channel into the profiler registry (shared shim: lazy
# import + swallow-all, log-once lives in profiler.safe_record).
_record = functools.partial(trace_record, "overlap")


def reduce_schedule(plan: "GradBuckets", mesh: Mesh, *,
                    reduce_op: str = "all_reduce",
                    hierarchy: str = "auto"
                    ) -> Tuple[List[Tuple[str, list]], Tuple[str, ...],
                               int, bool]:
    """THE per-bucket reduce schedule — one derivation shared by the accum
    engine (which executes it) and the static analyzer (which audits the
    traced program against it; if they ever derived it separately the
    audit would drift from the code it checks).

    Each bucket gets ``(mode, post_groups)``: mode fixes the in-scan
    collective + accumulator shape; post_groups are the psum axis groups
    issued after the scatter — hierarchical keeps the DCN hop its OWN
    collective so the scheduler can slide it independently of the ICI
    phase.

    * ``"scatter"``: psum_scatter over fsdp into the ZeRO-3 shard layout
    * ``"rs"``:      psum_scatter over the (padded) reduce group + tail AG
    * ``"ar"``:      plain psum

    Returns ``(sched, rs_axes, rs_group, hier)`` where ``rs_axes``/
    ``rs_group`` are the psum_scatter group of the ``"rs"`` buckets and
    ``hier`` says whether the DCN level exists.
    """
    if reduce_op not in ("all_reduce", "reduce_scatter"):
        raise ValueError(f"unknown reduce op {reduce_op!r} "
                         "(all_reduce|reduce_scatter)")
    if hierarchy not in ("auto", "flat", "hierarchical"):
        raise ValueError(f"unknown hierarchy {hierarchy!r} "
                         "(auto|flat|hierarchical)")
    axes = sync_axes(mesh)
    ici = ici_axes(mesh)
    dcn = dcn_axis(mesh)
    if hierarchy == "hierarchical" and dcn is None:
        raise ValueError(
            "hierarchy='hierarchical' needs a multi-slice mesh (slice "
            "axis > 1); build one with MeshSpec(slices=...)")
    hier = dcn is not None and hierarchy != "flat"
    ici_group = 1
    for a in ici:
        ici_group *= mesh.shape[a]
    group = sync_size(mesh)
    sched: List[Tuple[str, list]] = []
    for b in range(plan.n_buckets):
        if plan._is_scatter(b):
            if hier:
                post = [_present(mesh, tuple(a for a in ici if a != FSDP)),
                        (dcn,)]
            else:
                post = [_present(mesh,
                                 tuple(a for a in axes if a != FSDP))]
            sched.append(("scatter", [g for g in post if g]))
        elif hier:
            sched.append(("rs", [(dcn,)]))
        elif reduce_op == "reduce_scatter":
            sched.append(("rs", []))
        else:
            sched.append(("ar", []))
    rs_axes = ici if hier else axes
    rs_group = ici_group if hier else group
    return sched, rs_axes, rs_group, hier


def step_plans(params: Any, mesh: Mesh, *,
               bucket_bytes: int = DEFAULT_BUCKET_BYTES,
               param_specs: Optional[Any] = None,
               prefetch: int = 1):
    """``(plan, gather_plan)`` exactly as :func:`microbatch_grads` derives
    them for a step over ``params`` — the one planning entry the engine,
    the stepper's ``inspect`` hook, and the static analyzer all share.
    ``gather_plan`` is ``None`` for replicated (non-ZeRO-3) layouts."""
    from tony_tpu.parallel import sched as sched_mod  # lazy: no cycle

    if param_specs is None:
        return GradBuckets.plan(params, bucket_bytes), None
    fsdp_size = mesh.shape[FSDP] if FSDP in mesh.axis_names else 1
    plan = GradBuckets.plan_sharded(params, param_specs,
                                    shard_size=fsdp_size,
                                    bucket_bytes=bucket_bytes)
    return plan, sched_mod.GatherPlan.from_buckets(plan, prefetch=prefetch)


def region_param_specs(plan: "GradBuckets", param_specs: Any
                       ) -> Tuple[Any, List[Tuple[int, ...]]]:
    """Full-rank shard_map entry specs for a ZeRO-3 plan (shard_map wants
    one entry per dim). UNEVEN leaves — shard dim not divisible by fsdp,
    ``plan.shard_pads > 0`` — cross the region boundary REPLICATED:
    shard_map can't split an indivisible dim, so jax reshards them at
    entry and their grads exit whole (the scatter bucket still pads and
    reduces them bandwidth-optimally inside). Returns ``(p_specs,
    uneven_shapes)`` — shared by the accum engine and the fused-optimizer
    standalone step so both regions see the identical boundary layout."""
    spec_leaves = []
    uneven: List[Tuple[int, ...]] = []
    for i, s in enumerate(jax.tree.leaves(
            param_specs, is_leaf=lambda x: isinstance(x, P))):
        entries = list(tuple(s)) + [None] * (len(plan.shapes[i])
                                             - len(tuple(s)))
        if plan._pad(i):
            entries[plan.shard_dims[i]] = None
            uneven.append(plan.shapes[i])
        spec_leaves.append(P(*entries))
    return jax.tree.unflatten(plan.treedef, spec_leaves), uneven


def _present(mesh: Mesh, axes: Sequence[str]) -> Tuple[str, ...]:
    """Drop size-1 axes: a psum over them is a no-op the latency-hiding
    scheduler still has to place."""
    return tuple(a for a in axes if mesh.shape[a] > 1)


def microbatch_grads(loss_fn: Callable[[Any, Any], Any], params: Any,
                     batch: Any, mesh: Mesh, *, microbatches: int,
                     buckets: Optional[GradBuckets] = None,
                     bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                     reduce_op: str = "all_reduce",
                     has_aux: bool = False,
                     param_specs: Optional[Any] = None,
                     hierarchy: str = "auto",
                     gather: str = "bucketed",
                     prefetch: int = 1,
                     fused: Optional[Any] = None,
                     opt_slots: Optional[Any] = None,
                     opt_scal: Optional[jax.Array] = None,
                     quant_amax: Optional[Sequence[jax.Array]] = None):
    """Gradient accumulation over ``microbatches`` with per-bucket sync.

    ``loss_fn(params, microbatch) -> loss`` (or ``(loss, aux)`` with
    ``has_aux``) is the per-shard loss — a *mean* over its microbatch
    slice, collective-free (the engine owns all cross-device traffic, like
    ``gpipe``'s ``stage_fn`` contract). The batch's leading dim is split
    over the sync axes (slice × data × fsdp). Returns ``(loss, grads)``
    (or ``(loss, aux, grads)``): the global-mean loss and grads —
    numerically the monolithic full-batch step up to fp reassociation.

    **Replicated mode** (``param_specs=None``): params are replicated
    across the sync axes inside the region; grads come back replicated.

    **ZeRO-3 mode** (``param_specs`` = pytree of ``PartitionSpec``): params
    enter the region in their fsdp-shard layout; each microbatch gathers
    them for compute, but the grads are ``psum_scatter``-ed straight into
    the shard layout per shard-major bucket and never materialize
    replicated — the returned grads carry exactly ``param_specs``, ready
    for ``apply_gradients`` on a sharded optimizer state. EXCEPTION —
    uneven leaves (sharded dim not divisible by the fsdp size, which used
    to raise): their reduction still rides a zero-padded scatter bucket,
    but the leaf itself crosses the region boundary REPLICATED (shard_map
    cannot split an indivisible dim) and its grad comes back whole, so
    the per-leaf memory saving does not apply to it. Logged (WARNING,
    once per plan) so a large uneven leaf — e.g. a vocab embedding whose
    dim doesn't divide fsdp — can't silently eat the ZeRO-3 budget.

    **Forward gathers** (ZeRO-3 only; ``gather`` = ``"bucketed"`` |
    ``"per_leaf"``): each microbatch re-gathers the sharded params for
    compute. The default coalesces the per-leaf ``all_gather``s into the
    SAME shard-major buckets the scatter plan uses (one collective per
    bucket — bit-exact vs per-leaf, it is pure data movement) and chains
    bucket *k*'s gather on bucket *k−prefetch*'s completion
    (:class:`tony_tpu.parallel.sched.GatherPlan`), so the next bucket's
    gather rides under this bucket's layer compute while replicated
    params never materialize outside the live bucket window.
    ``"per_leaf"`` is the pre-scheduler path, kept as the numerics pin.

    **Hierarchy** (``"auto"`` | ``"flat"`` | ``"hierarchical"``): on a
    multi-slice mesh (``slice`` axis > 1) the auto/hierarchical reduce is
    two-level — ``psum_scatter`` over the intra-slice ICI axes per bucket,
    then a small per-bucket allreduce over the DCN ``slice`` axis, both
    issued inside the scan so the DCN hop hides under the next
    microbatch's backward and the next bucket's ICI phase; the shards are
    re-gathered over ICI once, after the scan. ``"flat"`` forces the
    single-level reduce over the whole sync group (the numerics pin for
    the hierarchical path).

    Inside the scan body each microbatch's grads are reduced bucket by
    bucket, so the collective for microbatch *i* is in flight while
    microbatch *i+1*'s forward/backward computes (the Horovod overlap,
    expressed for XLA's latency-hiding scheduler — see
    :func:`overlap_xla_flags`).

    **Fused optimizer update** (``fused`` =
    :class:`tony_tpu.ops.fused_optim.FusedOptimizer`, with ``opt_slots``
    its bucket-resident slot buffers and ``opt_scal`` the per-step scalar
    vector): instead of unpacking the reduced bucket buffers into leaf
    grads, the optimizer update runs IN the region, bucket by bucket, on
    the very accumulators the scan produced — reduce → update never
    leaves the bucket domain, and scatter buckets stay in the shard
    layout throughout. The return changes to ``(loss[, aux], new_params,
    new_slots, grad_norm)`` where the norm is the bucket-major global
    grad norm (post-reduce, pre-clip).

    **Quantized gathers** (``quant_amax`` = per-gather-bucket f32
    ``[window]`` amax histories, replicated — see
    :mod:`tony_tpu.ops.quant`): the bucketed forward gathers ship int8.
    Scales are DELAYED — derived from the history the state carries, so
    every shard quantizes with the identical scale and the int8 wire
    format is bit-exact against quantize-after-gather. The region
    measures the current bucket amax once at entry (local max + ``pmax``
    over fsdp — the params don't change inside the scan) and rolls it
    into the history; the updated histories append to the return
    (``..., new_amax``). ZeRO-3 + ``gather="bucketed"`` only.
    """
    from tony_tpu.parallel import sched as sched_mod  # lazy: no cycle

    axes = sync_axes(mesh)
    group = sync_size(mesh)
    dcn = dcn_axis(mesh)
    if gather not in ("bucketed", "per_leaf"):
        raise ValueError(f"unknown gather mode {gather!r} "
                         "(bucketed|per_leaf)")
    lead = jax.tree.leaves(batch)[0].shape[0]
    if lead % (group * microbatches):
        raise ValueError(
            f"global batch {lead} not divisible by sync group {group} x "
            f"microbatches {microbatches} (= {group * microbatches})")

    zero3 = param_specs is not None
    gplan = None
    if zero3:
        # The forward-gather schedule is resolved HERE, once per plan —
        # which leaves gather, on which dim, in which bucket. The scan
        # body below just drives the static lists (the spec probing that
        # used to run per gather_params call is gone from the traced
        # path).
        if buckets is not None:
            plan = buckets
            gplan = sched_mod.GatherPlan.from_buckets(plan,
                                                      prefetch=prefetch)
        else:
            plan, gplan = step_plans(params, mesh,
                                     bucket_bytes=bucket_bytes,
                                     param_specs=param_specs,
                                     prefetch=prefetch)
        p_specs, uneven = region_param_specs(plan, param_specs)
        if uneven:
            # Loud on purpose: these leaves lose the ZeRO-3 per-leaf
            # memory saving (replicated at the boundary, whole grads) —
            # a big uneven leaf deserves a reshape, not a silent OOM.
            _log.warning(
                "ZeRO-3 plan: %d leaf(s) with fsdp-indivisible sharded "
                "dims (shapes %s) are replicated at the accum-region "
                "boundary; their grads reduce via padded scatter buckets "
                "but return whole", len(uneven), uneven[:4])
    else:
        plan = buckets if buckets is not None else GradBuckets.plan(
            params, bucket_bytes)
        p_specs = jax.tree.map(lambda _: P(), params)
    quant = quant_amax is not None
    if quant:
        if not zero3 or gather != "bucketed":
            raise ValueError(
                "quantize-on-gather (quant_amax=) needs the ZeRO-3 "
                "bucketed gather path (fsdp-sharded params, "
                "gather='bucketed') — the int8 lane lives on the "
                "GatherPlan bucket boundary")
        from tony_tpu.ops import quant as _quant_mod

        _quant_mod.check_quant_amax(gplan, quant_amax)
    b_specs = jax.tree.map(lambda _: P(axes), batch)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=has_aux)

    # Per-bucket reduce schedule, resolved at trace time — ONE derivation
    # shared with the static analyzer (see :func:`reduce_schedule`).
    sched, rs_axes, rs_group, hier = reduce_schedule(
        plan, mesh, reduce_op=reduce_op, hierarchy=hierarchy)

    levels: List[Dict[str, object]] = []
    if zero3 and plan.n_scatter_buckets:
        levels.append({
            "level": "ici", "op": "psum_scatter", "axes": [FSDP],
            "bucket_nbytes": [n if plan._is_scatter(b) else 0
                              for b, n in enumerate(plan.bucket_nbytes)]})
    # A flat reduce on a multi-slice mesh spans BOTH transports in one
    # collective — label it so, or the report would claim the cross-slice
    # hop rides ICI.
    flat_level = "ici" if dcn is None or hier else "ici+dcn"
    if any(m == "rs" for m, _ in sched):
        levels.append({
            "level": "ici" if hier else flat_level, "op": "psum_scatter",
            "axes": list(rs_axes),
            "bucket_nbytes": [n if m == "rs" else 0 for (m, _), n in
                              zip(sched, plan.bucket_nbytes)]})
    if any(m == "ar" for m, _ in sched):
        levels.append({
            "level": flat_level, "op": "all_reduce", "axes": list(axes),
            "bucket_nbytes": [n if m == "ar" else 0 for (m, _), n in
                              zip(sched, plan.bucket_nbytes)]})
    if hier:
        # The DCN hop moves one scattered chunk per bucket.
        def _chunk(b):
            numel, item = plan.bucket_numel[b], \
                plan.dtypes[plan.buckets[b][0]].itemsize
            if sched[b][0] == "scatter":
                return (numel // plan.shard_size) * item
            padded = numel + ((-numel) % rs_group)
            return (padded // rs_group) * item
        levels.append({
            "level": "dcn", "op": "all_reduce", "axes": [dcn],
            "bucket_nbytes": [_chunk(b) for b in range(plan.n_buckets)]})
    _record("accum_step", n_buckets=plan.n_buckets,
            bucket_nbytes=list(plan.bucket_nbytes),
            threshold=plan.threshold, microbatches=microbatches,
            reduce_op=reduce_op, sync_group=group,
            hierarchy="hierarchical" if hier else "flat",
            zero3=zero3, n_scatter_buckets=plan.n_scatter_buckets,
            n_padded_buckets=sum(1 for b in range(plan.n_buckets)
                                 if plan._is_padded(b)),
            levels=levels)
    # Mirror the whole schedule into the unified collective registry: the
    # reduce levels plus (ZeRO-3) the forward gathers, so every transfer
    # in the step shows up in profiler.collective_report().
    sched_mod.record_reduce_levels("accum", levels)
    if zero3 and gplan.gather_leaves:
        if gather == "bucketed":
            # The quantized lane ships int8 on the wire: 1 B/element
            # instead of the bucket dtype's itemsize.
            nbytes = [plan.bucket_numel[b] for b in gplan.gather_buckets] \
                if quant else list(gplan.gather_nbytes)
        else:
            nbytes = [
                int(np.prod(plan.shapes[i], dtype=np.int64))
                * plan.dtypes[i].itemsize for i, _ in gplan.gather_leaves]
        sched_mod.record_collective(
            "accum.fwd_gather", kind="all_gather", plane="fwd_gather",
            axes=[FSDP], nbytes=nbytes, gather=gather,
            quant="int8" if quant else None,
            prefetch=gplan.prefetch if gather == "bucketed" else None,
            per_microbatch=microbatches)
    if quant:
        raw = list(gplan.gather_nbytes)
        q_nb = [plan.bucket_numel[b] for b in gplan.gather_buckets]
        trace_record(
            "quant", "accum_gather", n_buckets=gplan.n_gather_buckets,
            window=int(quant_amax[0].shape[0]) if quant_amax else 0,
            raw_nbytes=raw, int8_nbytes=q_nb,
            bytes_saved=sum(raw) - sum(q_nb),
            per_microbatch=microbatches)

    def gather_params(p, scales=None):
        if not zero3:
            return p
        leaves = list(jax.tree.leaves(p))
        if gather == "bucketed":
            return jax.tree.unflatten(plan.treedef,
                                      gplan.gather(leaves, scales=scales))
        # Per-leaf pin path: replicated/scalar/uneven leaves entered the
        # region whole and are not in the (static) drive list.
        for i, d in gplan.gather_leaves:
            leaves[i] = jax.lax.all_gather(leaves[i], FSDP, axis=d,
                                           tiled=True)
        return jax.tree.unflatten(plan.treedef, leaves)

    def spmd(params, local, slots=None, scal=None, qamax=None):
        scales = None
        new_amax: List[jax.Array] = []
        if quant:
            from tony_tpu.ops import quant as quant_mod

            # Delayed scaling: THIS step quantizes with the scale the
            # state carried in (identical on every shard — the int8
            # gather's exactness rests on that); the CURRENT amax is
            # measured once at region entry (params are loop-invariant
            # inside the scan) and rolled into the history for the next
            # step, the same in-region cadence as PR 7's opt slots.
            leaves0 = jax.tree.leaves(params)
            scales = [quant_mod.hist_scale(h) for h in qamax]
            for k, b in enumerate(gplan.gather_buckets):
                m = jax.lax.pmax(quant_mod.bucket_amax(
                    [leaves0[i] for i in plan.buckets[b]]), gplan.axis)
                new_amax.append(quant_mod.push_amax(qamax[k], m))
        mbs = jax.tree.map(
            lambda x: x.reshape((microbatches, x.shape[0] // microbatches)
                                + x.shape[1:]), local)
        acc0 = []
        for b, (idxs, n) in enumerate(zip(plan.buckets, plan.bucket_numel)):
            dt = plan.dtypes[idxs[0]]
            mode, _ = sched[b]
            if mode == "scatter":
                n = n // plan.shard_size
            elif mode == "rs":
                n = (n + ((-n) % rs_group)) // rs_group   # padded shard
            acc0.append(jnp.zeros((n,), dt))

        def body(carry, mb):
            loss_acc, aux_acc, acc = carry
            out, grads = grad_fn(gather_params(params, scales), mb)
            loss, aux = out if has_aux else (out, jnp.float32(0.0))
            bufs = plan.pack(grads)
            nxt = []
            for b, (a, buf) in enumerate(zip(acc, bufs)):
                mode, post = sched[b]
                if mode == "scatter":
                    s = jax.lax.psum_scatter(buf, FSDP, tiled=True)
                elif mode == "rs":
                    pad = (-buf.shape[0]) % rs_group
                    if pad:
                        buf = jnp.concatenate(
                            [buf, jnp.zeros((pad,), buf.dtype)])
                    s = jax.lax.psum_scatter(buf, rs_axes, tiled=True)
                else:
                    s = jax.lax.psum(buf, axes)
                for g in post:
                    s = jax.lax.psum(s, g)
                nxt.append(a + s)
            return (loss_acc + loss, aux_acc + aux, nxt), None

        (loss, aux, acc), _ = jax.lax.scan(
            body, (jnp.float32(0.0), jnp.float32(0.0), acc0), mbs)
        denom = microbatches * group
        if fused is not None:
            # Fused-optimizer tail: mean-scale the bucket accumulators
            # ("rs" buckets re-gather once first — their leaves live
            # replicated) and hand them STRAIGHT to the in-region update;
            # the leaf-grad pytree never materializes.
            g_bufs = []
            for b, (a, n) in enumerate(zip(acc, plan.bucket_numel)):
                if sched[b][0] == "rs":
                    a = jax.lax.all_gather(a, rs_axes, tiled=True)[:n]
                g_bufs.append(a / denom)
            new_leaves, new_slots, gnorm = fused.region_apply(
                plan, jax.tree.leaves(params), g_bufs, slots, scal,
                sharded=zero3 and plan.shard_size > 1)
            loss = jax.lax.psum(loss, axes) / denom
            aux = jax.lax.psum(aux, axes) / denom
            return (loss, aux,
                    jax.tree.unflatten(plan.treedef, new_leaves),
                    new_slots, gnorm) + ((new_amax,) if quant else ())
        # Tail: "rs" buckets re-gather ONCE over their scatter group;
        # even scatter buckets stay in the shard layout (that IS the
        # output); PADDED scatter buckets re-gather over fsdp and unpad —
        # their leaves exit the region whole.
        leaf_out: list = [None] * len(plan.shapes)
        for b, (a, n) in enumerate(zip(acc, plan.bucket_numel)):
            mode = sched[b][0]
            if mode == "rs":
                buf = jax.lax.all_gather(a, rs_axes, tiled=True)[:n]
                parts = plan.leaf_buffers(b, buf, layout="full")
            elif mode == "scatter" and plan._is_padded(b):
                buf = jax.lax.all_gather(a, FSDP, tiled=True)
                parts = plan.leaf_buffers(b, buf, layout="gathered")
            elif mode == "scatter":
                parts = plan.leaf_buffers(b, a, layout="shard")
            else:
                parts = plan.leaf_buffers(b, a, layout="full")
            for i, v in parts.items():
                leaf_out[i] = v
        tree = jax.tree.unflatten(plan.treedef, leaf_out)
        grads = jax.tree.map(lambda b: b / denom, tree)
        loss = jax.lax.psum(loss, axes) / denom
        aux = jax.lax.psum(aux, axes) / denom
        return (loss, aux, grads) + ((new_amax,) if quant else ())

    amax_specs = [P()] * len(quant_amax) if quant else None
    if fused is not None:
        if opt_slots is None or opt_scal is None:
            raise ValueError(
                "microbatch_grads(fused=...) needs opt_slots (the bucket-"
                "resident slot buffers) and opt_scal (FusedOptimizer"
                ".scalars(count))")
        fused.check_slots(plan, opt_slots)
        bspecs_f = fused.bucket_specs(plan)
        slot_specs = {n: list(bspecs_f) for n in fused.slot_names}
        fused.record("accum_update", plan, microbatches=microbatches)
        in_specs = (p_specs, b_specs, slot_specs, P())
        out_specs = (P(), P(), p_specs, slot_specs, P())
        args = (params, batch, opt_slots, opt_scal)
        if quant:
            in_specs += (amax_specs,)
            out_specs += (amax_specs,)
            args += (list(quant_amax),)
        outs = compat.shard_map(spmd, mesh, in_specs=in_specs,
                                out_specs=out_specs)(*args)
        loss, aux, new_params, new_slots, gnorm = outs[:5]
        tail = (outs[5],) if quant else ()
        if has_aux:
            return (loss, aux, new_params, new_slots, gnorm) + tail
        return (loss, new_params, new_slots, gnorm) + tail
    if quant:
        loss, aux, grads, new_hist = compat.shard_map(
            lambda p, l, qa: spmd(p, l, qamax=qa), mesh,
            in_specs=(p_specs, b_specs, amax_specs),
            out_specs=(P(), P(), p_specs, amax_specs))(
                params, batch, list(quant_amax))
        if has_aux:
            return loss, aux, grads, new_hist
        return loss, grads, new_hist
    loss, aux, grads = compat.shard_map(
        spmd, mesh, in_specs=(p_specs, b_specs),
        out_specs=(P(), P(), p_specs))(params, batch)
    if has_aux:
        return loss, aux, grads
    return loss, grads
