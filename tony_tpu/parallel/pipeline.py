"""Pipeline parallelism: a GPipe schedule over the ``pipe`` mesh axis.

Absent from the reference (SURVEY.md §2.3 "Pipeline parallel (PP)" — TonY
delegates all parallelism and no runtime implements PP); built here as the
TPU-native equivalent: stages are laid out over the ``pipe`` mesh axis and
microbatches flow stage-to-stage via ``jax.lax.ppermute`` (ICI neighbor
RDMA), the collective-permute pipelining pattern XLA/GSPMD programs use
instead of framework-level send/recv threads. The whole schedule is one
``lax.scan`` inside one ``shard_map`` — a single compiled program, no host
round trips; the backward pass is plain autodiff (reversed ``ppermute``
ring → the reverse pipeline), so training works through ``jax.grad``
unchanged.

Schedule: classic GPipe fill/drain. With S stages and M microbatches the
scan runs ``M + S - 1`` ticks; stage 0 ingests microbatch ``t`` at tick
``t``, stage ``S-1`` emits microbatch ``t-(S-1)``'s result; bubble fraction
is ``(S-1)/(M+S-1)`` — callers pick ``M ≥ 4·S`` to amortize.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tony_tpu.parallel import DATA, FSDP, PIPE


def stage_split(params: Any, n_stages: int) -> Any:
    """Reshape scan-stacked layer params ``[L, ...]`` into pipeline-stage
    params ``[S, L/S, ...]`` (stage-major: stage s owns layers
    ``[s·L/S, (s+1)·L/S)``)."""
    def reshape(leaf):
        l = leaf.shape[0]
        if l % n_stages:
            raise ValueError(f"{l} layers not divisible by {n_stages} stages")
        return leaf.reshape((n_stages, l // n_stages) + leaf.shape[1:])
    return jax.tree.map(reshape, params)


def gpipe(stage_fn: Callable[[Any, jax.Array], jax.Array],
          stage_params: Any, x: jax.Array, mesh: Mesh, *,
          microbatches: int, pipe_axis: str = PIPE) -> jax.Array:
    """Run ``x`` through ``S = mesh.shape[pipe_axis]`` pipelined stages.

    Args:
      stage_fn: ``(params_slice, mb) -> mb_out`` — one stage's compute on
        one microbatch. Pure per-device function (no collectives); shapes
        of ``mb_out`` must equal ``mb`` (uniform stages, the usual
        transformer-block case).
      stage_params: pytree whose leaves have leading dim ``S``; sharded
        over ``pipe_axis`` so each device group holds one stage's slice
        (build with :func:`stage_split`).
      x: global batch ``[B, ...]``, batch dim sharded over the DP axes as
        usual; ``B_local`` must divide by ``microbatches``.
      mesh: the device mesh; composes with data parallelism (each DP group
        runs its own pipeline) — tensor/seq axes must be 1 inside
        ``stage_fn`` (keep it collective-free).

    Returns the last stage's outputs in original batch order, replicated
    over ``pipe_axis`` (like any GSPMD activation).
    """
    n_stages = mesh.shape[pipe_axis]
    dp_axes = tuple(a for a in (DATA, FSDP) if a in mesh.axis_names)
    dp_size = 1
    for a in dp_axes:
        dp_size *= mesh.shape[a]
    local = x.shape[0] // dp_size
    if local % microbatches:
        raise ValueError(
            f"per-DP-group batch {local} (global {x.shape[0]} / dp "
            f"{dp_size}) not divisible by microbatches={microbatches}")
    x_spec = P(dp_axes or None)
    p_specs = jax.tree.map(lambda _: P(pipe_axis), stage_params)

    def spmd(params, x_local):
        params = jax.tree.map(lambda a: jnp.squeeze(a, 0), params)
        idx = jax.lax.axis_index(pipe_axis)
        m = microbatches
        mbs = x_local.reshape((m, x_local.shape[0] // m)
                              + x_local.shape[1:])
        outs0 = jnp.zeros_like(mbs)
        buf0 = jnp.zeros_like(mbs[0])
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(carry, t):
            buf, outs = carry
            # Stage 0 ingests microbatch t (clamped past M: those results
            # never reach the output window below).
            inp = jax.lax.dynamic_index_in_dim(
                mbs, jnp.minimum(t, m - 1), 0, keepdims=False)
            cur = jnp.where(idx == 0, inp, buf)
            y = stage_fn(params, cur)
            # Last stage emits microbatch t-(S-1) once the pipe is full.
            oidx = jnp.clip(t - (n_stages - 1), 0, m - 1)
            emit = jnp.logical_and(idx == n_stages - 1, t >= n_stages - 1)
            prev = jax.lax.dynamic_index_in_dim(outs, oidx, 0,
                                                keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(emit, y, prev), oidx, 0)
            # Rotate: stage i's output becomes stage i+1's next input
            # (devices with no sender receive zeros; stage 0 overwrites).
            buf = jax.lax.ppermute(y, pipe_axis, perm)
            return (buf, outs), None

        (_, outs), _ = jax.lax.scan(
            tick, (buf0, outs0), jnp.arange(m + n_stages - 1))
        # Only the last stage wrote non-zeros; psum broadcasts its result
        # to the whole pipe group.
        outs = jax.lax.psum(outs, pipe_axis)
        return outs.reshape(x_local.shape)

    return jax.shard_map(
        spmd, mesh=mesh, in_specs=(p_specs, x_spec), out_specs=x_spec,
        check_vma=False)(stage_params, x)


def pipelined_lm_logits(params: Any, tokens: jax.Array, cfg: Any,
                        mesh: Mesh, *, n_stages: int,
                        microbatches: int) -> jax.Array:
    """Transformer forward with the scanned block stack run as a GPipe.

    ``params`` is a :class:`~tony_tpu.models.transformer.Transformer`
    param tree built with ``scan_layers=True`` (block params stacked
    ``[L, ...]``); embedding and lm_head run outside the pipeline (they
    are DP/TP work, not stage work). Shared by the multi-chip dryrun and
    the pipeline tests so the composition has one source of truth.

    The embed/head tail here deliberately mirrors ``Transformer.__call__``
    (bf16 embed cast, bf16 lm_head matmul, f32 logits) — flax compact
    modules can't expose their head as a separately-applicable method
    without restructuring; ``test_pipelined_llama_blocks_match_and_train``
    pins this copy against ``model.apply`` so drift fails loudly.
    """
    from tony_tpu.models.transformer import Block, RMSNorm  # lazy: no cycle

    positions = jnp.arange(tokens.shape[1])
    block = Block(cfg)

    def stage_fn(block_params, x):
        def body(h, lp):
            return block.apply({"params": lp}, h, positions), None
        h, _ = jax.lax.scan(body, x, block_params)
        return h

    x = jnp.take(params["embedding"], tokens, axis=0).astype(cfg.dtype)
    x = gpipe(stage_fn, stage_split(params["layers"]["block"], n_stages),
              x, mesh, microbatches=microbatches)
    x = RMSNorm(cfg.norm_eps).apply({"params": params["final_norm"]}, x)
    logits = x @ params["lm_head"]["kernel"].astype(cfg.dtype)
    return logits.astype(jnp.float32)
