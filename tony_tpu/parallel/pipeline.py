"""Pipeline parallelism: a GPipe schedule over the ``pipe`` mesh axis.

Absent from the reference (SURVEY.md §2.3 "Pipeline parallel (PP)" — TonY
delegates all parallelism and no runtime implements PP); built here as the
TPU-native equivalent: stages are laid out over the ``pipe`` mesh axis and
microbatches flow stage-to-stage via ``jax.lax.ppermute`` (ICI neighbor
RDMA), the collective-permute pipelining pattern XLA/GSPMD programs use
instead of framework-level send/recv threads. The whole schedule is one
``lax.scan`` inside one ``shard_map`` — a single compiled program, no host
round trips; the backward pass is plain autodiff (reversed ``ppermute``
ring → the reverse pipeline), so training works through ``jax.grad``
unchanged.

Schedule: classic GPipe fill/drain. With S stages and M microbatches the
scan runs ``M + S - 1`` ticks; stage 0 ingests microbatch ``t`` at tick
``t``, stage ``S-1`` emits microbatch ``t-(S-1)``'s result; bubble fraction
is ``(S-1)/(M+S-1)`` — callers pick ``M ≥ 4·S`` to amortize.

:func:`gpipe_1f1b` is the memory-lean upgrade: a ``jax.custom_vjp`` over
the same ring whose backward is an explicitly scheduled reverse pipeline
with stage-granularity rematerialization (the 1F1B discipline: in the
steady state each stage runs one recompute-forward and one backward per
tick). ``gpipe`` stays as the reference implementation it is numerically
pinned against.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import numpy as np

from tony_tpu import compat
from tony_tpu.parallel import DATA, FSDP, PIPE  # noqa: F401 (PIPE is API)
from tony_tpu.parallel import sched as _sched
from tony_tpu.parallel.overlap import (_record as _record_schedule,
                                       sync_axes, sync_size)


def _mb_nbytes(x: jax.Array, dp_size: int, microbatches: int) -> int:
    """Bytes of ONE microbatch buffer on one pipeline edge — what each
    ``ppermute`` tick moves between neighbor stages."""
    rows = x.shape[0] // max(dp_size, 1) // microbatches
    return int(rows * np.prod(x.shape[1:], dtype=np.int64)
               * np.dtype(x.dtype).itemsize)


def _local_batch(x: jax.Array, dp_size: int, microbatches: int) -> int:
    """Per-DP-group batch size, validated: an indivisible global batch
    must fail loudly (floor-division silently DROPPED the remainder rows
    of every DP group before this check existed)."""
    if x.shape[0] % dp_size:
        raise ValueError(
            f"global batch {x.shape[0]} not divisible by the DP group "
            f"count {dp_size}; {x.shape[0] % dp_size} rows would be "
            f"silently dropped")
    local = x.shape[0] // dp_size
    if local % microbatches:
        raise ValueError(
            f"per-DP-group batch {local} (global {x.shape[0]} / dp "
            f"{dp_size}) not divisible by microbatches={microbatches}")
    return local


def stage_split(params: Any, n_stages: int) -> Any:
    """Reshape scan-stacked layer params ``[L, ...]`` into pipeline-stage
    params ``[S, L/S, ...]`` (stage-major: stage s owns layers
    ``[s·L/S, (s+1)·L/S)``)."""
    def reshape(leaf):
        l = leaf.shape[0]
        if l % n_stages:
            raise ValueError(f"{l} layers not divisible by {n_stages} stages")
        return leaf.reshape((n_stages, l // n_stages) + leaf.shape[1:])
    return jax.tree.map(reshape, params)


def gpipe(stage_fn: Callable[[Any, jax.Array], jax.Array],
          stage_params: Any, x: jax.Array, mesh: Mesh, *,
          microbatches: int, pipe_axis: str = PIPE) -> jax.Array:
    """Run ``x`` through ``S = mesh.shape[pipe_axis]`` pipelined stages.

    Args:
      stage_fn: ``(params_slice, mb) -> mb_out`` — one stage's compute on
        one microbatch. Pure per-device function (no collectives); shapes
        of ``mb_out`` must equal ``mb`` (uniform stages, the usual
        transformer-block case).
      stage_params: pytree whose leaves have leading dim ``S``; sharded
        over ``pipe_axis`` so each device group holds one stage's slice
        (build with :func:`stage_split`).
      x: global batch ``[B, ...]``, batch dim sharded over the DP axes as
        usual; ``B_local`` must divide by ``microbatches``.
      mesh: the device mesh; composes with data parallelism (each DP group
        runs its own pipeline) — tensor/seq axes must be 1 inside
        ``stage_fn`` (keep it collective-free).

    Returns the last stage's outputs in original batch order, replicated
    over ``pipe_axis`` (like any GSPMD activation).
    """
    n_stages = mesh.shape[pipe_axis]
    dp_axes, dp_size = sync_axes(mesh), sync_size(mesh)
    local = _local_batch(x, dp_size, microbatches)
    x_spec = P(dp_axes or None)
    p_specs = jax.tree.map(lambda _: P(pipe_axis), stage_params)

    def spmd(params, x_local):
        params = jax.tree.map(lambda a: jnp.squeeze(a, 0), params)
        idx = jax.lax.axis_index(pipe_axis)
        m = microbatches
        mbs = x_local.reshape((m, x_local.shape[0] // m)
                              + x_local.shape[1:])
        outs0 = jnp.zeros_like(mbs)
        buf0 = jnp.zeros_like(mbs[0])
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(carry, t):
            buf, outs = carry
            # Stage 0 ingests microbatch t (clamped past M: those results
            # never reach the output window below).
            inp = jax.lax.dynamic_index_in_dim(
                mbs, jnp.minimum(t, m - 1), 0, keepdims=False)
            cur = jnp.where(idx == 0, inp, buf)
            y = stage_fn(params, cur)
            # Last stage emits microbatch t-(S-1) once the pipe is full.
            oidx = jnp.clip(t - (n_stages - 1), 0, m - 1)
            emit = jnp.logical_and(idx == n_stages - 1, t >= n_stages - 1)
            prev = jax.lax.dynamic_index_in_dim(outs, oidx, 0,
                                                keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(emit, y, prev), oidx, 0)
            # Rotate: stage i's output becomes stage i+1's next input
            # (devices with no sender receive zeros; stage 0 overwrites).
            buf = jax.lax.ppermute(y, pipe_axis, perm)
            return (buf, outs), None

        (_, outs), _ = jax.lax.scan(
            tick, (buf0, outs0), jnp.arange(m + n_stages - 1))
        # Only the last stage wrote non-zeros; psum broadcasts its result
        # to the whole pipe group.
        outs = jax.lax.psum(outs, pipe_axis)
        return outs.reshape(x_local.shape)

    _record_schedule("gpipe", stages=n_stages, microbatches=microbatches,
                     ticks=microbatches + n_stages - 1)
    _sched.record_pipeline_edges(
        "gpipe", stages=n_stages, microbatches=microbatches,
        mb_nbytes=_mb_nbytes(x, dp_size, microbatches))
    return compat.shard_map(
        spmd, mesh, in_specs=(p_specs, x_spec),
        out_specs=x_spec)(stage_params, x)


def gpipe_1f1b(stage_fn: Callable[[Any, jax.Array], jax.Array],
               stage_params: Any, x: jax.Array, mesh: Mesh, *,
               microbatches: int, pipe_axis: str = PIPE) -> jax.Array:
    """GPipe ring with a 1F1B-disciplined backward via ``jax.custom_vjp``.

    Same contract and forward schedule (and therefore identical outputs)
    as :func:`gpipe`; the difference is who owns the backward. ``gpipe``
    leaves it to autodiff, which saves every scan tick's full ``stage_fn``
    residuals — ``(M+S-1)`` microbatches' worth of stage-internal
    activations per stage. Here the forward saves ONLY each microbatch's
    stage *input* (``M`` small buffers), and the backward is an explicitly
    scheduled reverse pipeline: cotangents enter at stage ``S-1`` and ride
    the reversed ring; each tick a stage recomputes one microbatch's
    forward under ``jax.vjp`` and immediately consumes it (the
    one-forward-one-backward steady state), so full stage-internal
    residency drops from ``M`` in-flight microbatches to the single
    microbatch being rematerialized. Bubble is unchanged — the win is
    activation memory, which is what caps ``M`` (and a bigger ``M`` is
    what shrinks the fill/drain bubble ``(S-1)/(M+S-1)``).
    """
    n_stages = mesh.shape[pipe_axis]
    dp_axes, dp_size = sync_axes(mesh), sync_size(mesh)
    _local_batch(x, dp_size, microbatches)
    m = microbatches
    x_spec = P(dp_axes or None)
    p_specs = jax.tree.map(lambda _: P(pipe_axis), stage_params)
    # Saved stage inputs: per device [1, M, mb...] -> global [S, M, ...]
    # with the batch dim still on the DP axes (same trick as the [S, ...]
    # stage params: the leading axis IS the pipe placement).
    saved_spec = P(pipe_axis, None, dp_axes or None)
    _record_schedule("gpipe_1f1b", stages=n_stages, microbatches=m,
                     ticks=2 * (m + n_stages - 1))
    _sched.record_pipeline_edges(
        "gpipe_1f1b", stages=n_stages, microbatches=m,
        mb_nbytes=_mb_nbytes(x, dp_size, m), reverse=True)

    def fwd_spmd(params, x_local):
        params = jax.tree.map(lambda a: jnp.squeeze(a, 0), params)
        idx = jax.lax.axis_index(pipe_axis)
        mbs = x_local.reshape((m, x_local.shape[0] // m)
                              + x_local.shape[1:])
        outs0 = jnp.zeros_like(mbs)
        saved0 = jnp.zeros_like(mbs)
        buf0 = jnp.zeros_like(mbs[0])
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(carry, t):
            buf, outs, saved = carry
            inp = jax.lax.dynamic_index_in_dim(
                mbs, jnp.minimum(t, m - 1), 0, keepdims=False)
            cur = jnp.where(idx == 0, inp, buf)
            # This stage sees microbatch t-idx this tick; bank its input
            # (the only residual the backward needs).
            midx = jnp.clip(t - idx, 0, m - 1)
            valid = jnp.logical_and(t - idx >= 0, t - idx < m)
            prev = jax.lax.dynamic_index_in_dim(saved, midx, 0,
                                                keepdims=False)
            saved = jax.lax.dynamic_update_index_in_dim(
                saved, jnp.where(valid, cur, prev), midx, 0)
            y = stage_fn(params, cur)
            oidx = jnp.clip(t - (n_stages - 1), 0, m - 1)
            emit = jnp.logical_and(idx == n_stages - 1, t >= n_stages - 1)
            prev_o = jax.lax.dynamic_index_in_dim(outs, oidx, 0,
                                                  keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(emit, y, prev_o), oidx, 0)
            buf = jax.lax.ppermute(y, pipe_axis, perm)
            return (buf, outs, saved), None

        (_, outs, saved), _ = jax.lax.scan(
            tick, (buf0, outs0, saved0), jnp.arange(m + n_stages - 1))
        outs = jax.lax.psum(outs, pipe_axis)
        return outs.reshape(x_local.shape), saved[None]

    def bwd_spmd(params, saved, dy_local):
        params = jax.tree.map(lambda a: jnp.squeeze(a, 0), params)
        saved = jnp.squeeze(saved, 0)
        idx = jax.lax.axis_index(pipe_axis)
        dys = dy_local.reshape((m, dy_local.shape[0] // m)
                               + dy_local.shape[1:])
        dxs0 = jnp.zeros_like(dys)
        buf0 = jnp.zeros_like(dys[0])
        dp0 = jax.tree.map(jnp.zeros_like, params)
        # Reverse ring: stage i+1's input-cotangent is stage i's
        # output-cotangent.
        perm = [(i + 1, i) for i in range(n_stages - 1)]

        def tick(carry, u):
            buf, dparams, dxs = carry
            # Stage s handles microbatch u-(S-1-s): microbatch 0's
            # cotangent enters at stage S-1 at tick 0 and reaches stage 0
            # at tick S-1 — the mirror of the forward fill.
            rel = u - (n_stages - 1 - idx)
            valid = jnp.logical_and(rel >= 0, rel < m)
            midx = jnp.clip(rel, 0, m - 1)
            ct = jnp.where(idx == n_stages - 1,
                           jax.lax.dynamic_index_in_dim(dys, midx, 0,
                                                        keepdims=False),
                           buf)
            x_in = jax.lax.dynamic_index_in_dim(saved, midx, 0,
                                                keepdims=False)
            # Recompute-forward + backward for ONE microbatch (the 1F1B
            # steady state): residency is this tick's residuals only.
            _, vjp = jax.vjp(stage_fn, params, x_in)
            dp, dx = vjp(ct)
            dparams = jax.tree.map(
                lambda a, g: a + jnp.where(valid, g, jnp.zeros_like(g)),
                dparams, dp)
            emit = jnp.logical_and(idx == 0, valid)
            prev = jax.lax.dynamic_index_in_dim(dxs, midx, 0,
                                                keepdims=False)
            dxs = jax.lax.dynamic_update_index_in_dim(
                dxs, jnp.where(emit, dx, prev), midx, 0)
            buf = jax.lax.ppermute(dx, pipe_axis, perm)
            return (buf, dparams, dxs), None

        (_, dparams, dxs), _ = jax.lax.scan(
            tick, (buf0, dp0, dxs0), jnp.arange(m + n_stages - 1))
        dxs = jax.lax.psum(dxs, pipe_axis)
        if dp_axes:
            # Each DP group saw its own batch shard; the stage's param
            # grad is the sum over groups (the reduction GSPMD inserts
            # for gpipe's autodiff backward).
            dparams = jax.lax.psum(dparams, dp_axes)
        return (jax.tree.map(lambda a: a[None], dparams),
                dxs.reshape(dy_local.shape))

    @jax.custom_vjp
    def run(params, x):
        y, _ = compat.shard_map(
            fwd_spmd, mesh, in_specs=(p_specs, x_spec),
            out_specs=(x_spec, saved_spec))(params, x)
        return y

    def run_fwd(params, x):
        y, saved = compat.shard_map(
            fwd_spmd, mesh, in_specs=(p_specs, x_spec),
            out_specs=(x_spec, saved_spec))(params, x)
        return y, (params, saved)

    def run_bwd(res, dy):
        params, saved = res
        return compat.shard_map(
            bwd_spmd, mesh, in_specs=(p_specs, saved_spec, x_spec),
            out_specs=(p_specs, x_spec))(params, saved, dy)

    run.defvjp(run_fwd, run_bwd)
    return run(stage_params, x)


def pipelined_lm_logits(params: Any, tokens: jax.Array, cfg: Any,
                        mesh: Mesh, *, n_stages: int,
                        microbatches: int) -> jax.Array:
    """Transformer forward with the scanned block stack run as a GPipe.

    ``params`` is a :class:`~tony_tpu.models.transformer.Transformer`
    param tree built with ``scan_layers=True`` (block params stacked
    ``[L, ...]``); embedding and lm_head run outside the pipeline (they
    are DP/TP work, not stage work). Shared by the multi-chip dryrun and
    the pipeline tests so the composition has one source of truth.

    The embed/head tail here deliberately mirrors ``Transformer.__call__``
    (bf16 embed cast, bf16 lm_head matmul, f32 logits) — flax compact
    modules can't expose their head as a separately-applicable method
    without restructuring; ``test_pipelined_llama_blocks_match_and_train``
    pins this copy against ``model.apply`` so drift fails loudly.
    """
    from tony_tpu.models.transformer import Block, RMSNorm  # lazy: no cycle

    positions = jnp.arange(tokens.shape[1])
    block = Block(cfg)

    def stage_fn(block_params, x):
        def body(h, lp):
            return block.apply({"params": lp}, h, positions), None
        h, _ = jax.lax.scan(body, x, block_params)
        return h

    x = jnp.take(params["embedding"], tokens, axis=0).astype(cfg.dtype)
    x = gpipe(stage_fn, stage_split(params["layers"]["block"], n_stages),
              x, mesh, microbatches=microbatches)
    x = RMSNorm(cfg.norm_eps).apply({"params": params["final_norm"]}, x)
    logits = x @ params["lm_head"]["kernel"].astype(cfg.dtype)
    return logits.astype(jnp.float32)
