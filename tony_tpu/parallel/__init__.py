"""Parallelism layer: device meshes, sharding rules, and collectives.

The reference delegates ALL parallelism to the launched frameworks (SURVEY.md
§2.3: PS via ``TF_CONFIG``, ring-allreduce via Horovod/NCCL, DDP via c10d) —
TonY itself owns no tensor code. This package is the TPU-native replacement
for that delegated layer, built the way JAX programs scale (SURVEY.md §2.3
"TPU-build equivalent" column):

* one :class:`MeshSpec` describes the whole parallelism layout
  (dp/fsdp/pp/ep/sp/tp) and builds a :class:`jax.sharding.Mesh`;
* parameters and activations carry *logical* axis names; :data:`RULES` maps
  them onto mesh axes (GSPMD then inserts the collectives — ``psum`` for DP
  grads over ICI replaces NCCL allreduce, ``all_gather``/``reduce_scatter``
  for FSDP, ``ppermute`` rings for sequence parallelism);
* :mod:`tony_tpu.parallel.ring_attention` provides ring attention over the
  ``seq`` mesh axis for long-context training (SURVEY.md §5.7).

No NCCL, no MPI, no parameter server: the data plane is XLA collectives over
ICI intra-slice / DCN across slices.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Mesh axis names, outermost (most DCN-friendly) to innermost (most
# ICI-bandwidth-hungry). The slice axis IS the DCN boundary: collectives
# over it cross slices, everything else stays on ICI. Data-parallel axes
# next so cross-slice traffic is the cheap gradient allreduce;
# tensor-parallel innermost so its per-layer collectives ride the fastest
# ICI links.
SLICE = "slice"     # DCN data parallel: one index per TPU slice
DATA = "data"       # pure data parallel (replicated params)
FSDP = "fsdp"       # data parallel with sharded params/optimizer (ZeRO-3)
PIPE = "pipe"       # pipeline parallelism (GPipe over ppermute)
EXPERT = "expert"   # MoE expert parallelism
SEQ = "seq"         # sequence/context parallelism (ring attention)
MODEL = "model"     # tensor parallelism (megatron-style)

AXES: Tuple[str, ...] = (SLICE, DATA, FSDP, PIPE, EXPERT, SEQ, MODEL)

# Logical-axis → mesh-axis rules (flax linen logical partitioning format).
# Parameters: weights shard over fsdp on their "embed"-like dim and over
# model on their "heads/ffn/vocab"-like dim. Activations: batch over both
# data axes, sequence over the ring axis.
RULES: Tuple[Tuple[str, object], ...] = (
    ("batch", (SLICE, DATA, FSDP)),
    ("act_seq", SEQ),
    ("act_embed", None),   # activations' feature dim (params' "embed" is
                           # fsdp-sharded; mixing both in one array would
                           # double-map the fsdp axis)
    ("act_heads", MODEL),
    ("embed", FSDP),
    ("heads", MODEL),
    ("kv_heads", MODEL),
    ("ffn", MODEL),
    ("vocab", MODEL),
    ("expert", EXPERT),
    ("expert_dim", None),  # router logits' expert dim (tiny, replicated)
    ("stage", None),       # pipeline stages: scan-over-layers axis, unsharded
    ("norm", None),
)


@dataclass(frozen=True)
class MeshSpec:
    """One parallelism layout: how many ways along each axis.

    The product must equal the device count. ``dp`` is accumulated
    automatically when left at 0: remaining devices go to data parallelism —
    the common "fill the pod with DP" default. ``slices`` is the DCN-level
    data-parallel degree (one index per TPU slice; 1 = single-slice job);
    the overlap engine reduces over it separately from the ICI axes.
    """
    dp: int = 0
    fsdp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1
    slices: int = 1

    def resolved_dp(self, n_devices: int) -> int:
        rest = (self.slices * self.fsdp * self.pp * self.ep * self.sp
                * self.tp)
        if self.dp:
            return self.dp
        if n_devices % rest:
            raise ValueError(f"{n_devices} devices not divisible by "
                             f"slices*fsdp*pp*ep*sp*tp={rest}")
        return n_devices // rest

    def build(self, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        dp = self.resolved_dp(len(devices))
        shape = (self.slices, dp, self.fsdp, self.pp, self.ep, self.sp,
                 self.tp)
        if int(np.prod(shape)) != len(devices):
            raise ValueError(
                f"mesh shape {dict(zip(AXES, shape))} needs "
                f"{int(np.prod(shape))} devices, have {len(devices)}")
        arr = np.asarray(devices).reshape(shape)
        return Mesh(arr, AXES)


def make_mesh(n_devices: Optional[int] = None, **spec_kw) -> Mesh:
    """Convenience: ``make_mesh(tp=2, sp=4)`` over all (or the first N)
    local devices."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return MeshSpec(**spec_kw).build(devices)


def batch_sharding(mesh: Mesh, *, seq_axis: bool = False) -> NamedSharding:
    """Input-batch sharding: batch dim over the slice axis and both DP axes;
    optionally the sequence dim over the ring axis (long-context inputs)."""
    if seq_axis:
        return NamedSharding(mesh, P((SLICE, DATA, FSDP), SEQ))
    return NamedSharding(mesh, P((SLICE, DATA, FSDP)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def logical_sharding(mesh: Mesh, *logical_axes: Optional[str],
                     allow_unknown: bool = False) -> NamedSharding:
    """NamedSharding for an array whose dims carry the given logical axis
    names (None = unsharded dim), resolved through :data:`RULES`.

    Unknown names raise: a typo'd axis used to fall through ``get`` to
    ``None`` and silently replicate the dim — the worst failure mode for a
    sharding bug (correct numbers, wrong memory/traffic). Pass
    ``allow_unknown=True`` to deliberately leave unlisted names unsharded
    (e.g. model code carrying axes for a rule set layered elsewhere).
    """
    table = dict(RULES)
    spec = []
    for ax in logical_axes:
        if ax is None:
            spec.append(None)
        elif ax in table:
            spec.append(table[ax])
        elif allow_unknown:
            spec.append(None)
        else:
            raise ValueError(
                f"unknown logical axis {ax!r}: not in RULES "
                f"({sorted(table)}); pass allow_unknown=True to leave it "
                f"unsharded deliberately")
    return NamedSharding(mesh, P(*spec))


def shard_logical(mesh: Mesh, x: jax.Array, *logical_axes: Optional[str],
                  allow_unknown: bool = False) -> jax.Array:
    """Device-put ``x`` with :func:`logical_sharding`."""
    return jax.device_put(
        x, logical_sharding(mesh, *logical_axes,
                            allow_unknown=allow_unknown))


def constraint(x: jax.Array, mesh: Mesh, *logical_axes: Optional[str],
               allow_unknown: bool = False) -> jax.Array:
    """``with_sharding_constraint`` through the logical-axis rules — the
    in-jit annotation that steers GSPMD."""
    return jax.lax.with_sharding_constraint(
        x, logical_sharding(mesh, *logical_axes,
                            allow_unknown=allow_unknown))


from tony_tpu.parallel.ring_attention import (  # noqa: E402  (re-export)
    ring_attention, ring_attention_sharded)
from tony_tpu.parallel.pipeline import (  # noqa: E402  (re-export)
    gpipe, gpipe_1f1b, pipelined_lm_logits, stage_split)
from tony_tpu.parallel.overlap import (  # noqa: E402  (re-export)
    GradBuckets, fsdp_param_specs, microbatch_grads, overlap_xla_flags)
from tony_tpu.parallel.sched import (  # noqa: E402  (re-export)
    GatherPlan, moe_dispatch_ffn_combine)

__all__ = [
    "AXES", "SLICE", "DATA", "FSDP", "PIPE", "EXPERT", "SEQ", "MODEL",
    "RULES",
    "MeshSpec", "make_mesh", "batch_sharding", "replicated",
    "logical_sharding", "shard_logical", "constraint",
    "ring_attention", "ring_attention_sharded", "gpipe", "gpipe_1f1b",
    "pipelined_lm_logits", "stage_split",
    "GradBuckets", "fsdp_param_specs", "microbatch_grads",
    "overlap_xla_flags",
    "GatherPlan", "moe_dispatch_ffn_combine",
]
