"""History server: the observability portal over jhist event logs (layer L⊥).

Mirrors ``tony-history-server`` (upstream Play-framework app ≈3,000 LoC,
unverified — SURVEY.md §0/§2.2/§3.5): scan the history root's
``finished/``+``intermediate/`` dirs, parse each job's jhist, and render a job
list plus per-job config/events/metrics pages. The reference renders Twirl
templates behind Play; here the same read path (:func:`tony_tpu.events
.list_jobs` / :func:`~tony_tpu.events.read_events`) feeds either a terminal
renderer (``tony history list|show``) or a stdlib ``http.server`` portal
(``tony history serve``) — no web framework dependency.
"""

from __future__ import annotations

import html
import json
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any, Dict, List, Optional

from tony_tpu import events as ev
from tony_tpu.util import default_workdir


def default_history_dir() -> Optional[Path]:
    """The client workdir's per-job history dirs don't share one root; the
    conventional root is ``~/.tony-tpu/history``. Per-job
    ``tony.history.location`` overrides are honored by the workdir scan
    (:func:`_job_history_root`), not here."""
    root = Path.home() / ".tony-tpu" / "history"
    return root if root.is_dir() else None


def _job_history_root(jobdir: Path) -> Path:
    """One job's history root: its serialized conf's
    ``tony.history.location`` when set — the key the AM itself honors
    when it writes the jhist (and ``tony profile`` honors when it
    collects traces) — else the conventional ``<jobdir>/history``.
    Before this resolution `tony history` silently missed every job
    whose conf redirected the log."""
    from tony_tpu import constants
    from tony_tpu.conf import HISTORY_LOCATION, TonyConfig

    conf_path = jobdir / constants.TONY_JOB_JSON
    if conf_path.is_file():
        try:
            loc = TonyConfig.load(conf_path).get(HISTORY_LOCATION)
        except (OSError, ValueError):
            loc = None              # unreadable conf: scan falls back
        if loc:
            return Path(loc)
    return jobdir / "history"


def gather_jobs(history_dir: Optional[str | Path]) -> List[Dict[str, Any]]:
    """All jobs under a history root, or — when no single root exists —
    under every job root the client workdir knows: each jobdir's conf is
    resolved FIRST (``tony.history.location``), then the conventional
    ``<jobdir>/history`` fallback. Roots are deduped, so many jobs
    sharing one conf-pointed root list each job once."""
    if history_dir is not None:
        return list(ev.list_jobs(history_dir))
    roots: List[Path] = []
    root = default_history_dir()
    if root is not None:
        roots.append(root)
    workdir = default_workdir()
    if workdir.is_dir():
        for jobdir in sorted(workdir.iterdir()):
            if jobdir.is_dir():
                roots.append(_job_history_root(jobdir))
    jobs: List[Dict[str, Any]] = []
    seen = set()
    for r in roots:
        key = str(r.resolve())
        if key in seen or not r.is_dir():
            continue
        seen.add(key)
        jobs.extend(ev.list_jobs(r))
    return jobs


def find_job(app_id: str,
             history_dir: Optional[str | Path]) -> Optional[Dict[str, Any]]:
    for job in gather_jobs(history_dir):
        if job["app_id"] == app_id:
            return job
    return None


# Cap on rendered TASK_METRICS samples per task: a long job appends one
# sample per task per 5s, and rendering all of them makes the detail page
# O(runtime). Downsampled evenly, always keeping the newest sample.
MAX_TIMELINE_SAMPLES = 256


def _downsample(samples: List[Dict[str, Any]],
                limit: int = MAX_TIMELINE_SAMPLES) -> List[Dict[str, Any]]:
    n = len(samples)
    if n <= limit:
        return samples
    step = n / limit
    picked = [samples[min(n - 1, int(i * step))] for i in range(limit - 1)]
    picked.append(samples[-1])
    return picked


def billing_rollup(records: List[Dict[str, Any]],
                   conf_snapshot: Optional[Dict[str, Any]]) -> Dict[
                       str, Dict[str, float]]:
    """Per-tenant billed-token rollup, integrated reader-side from the
    SERVE_WINDOW ledger: each task's per-tenant ``tokens_per_s`` is
    held constant until its next window (left-Riemann), summed over the
    job, then multiplied by the tenant's QoS weight from the job's conf
    snapshot (``tony.serve.qos.tenants``; unlisted tenants bill at 1.0).
    Integrates over the RAW record stream — the downsampled portal
    timelines would under-integrate long jobs."""
    from tony_tpu.conf import SERVE_QOS_TENANTS
    from tony_tpu.serve.qos import parse_tenants

    weights: Dict[str, float] = {}
    raw = str((conf_snapshot or {}).get(SERVE_QOS_TENANTS, "") or "")
    if raw:
        try:
            weights = parse_tenants(raw)
        except ValueError:
            weights = {}            # malformed snapshot: bill at weight 1
    # tid -> (timestamp, {tenant: tokens_per_s}) of that task's last window.
    last: Dict[str, Any] = {}
    tokens: Dict[str, float] = {}
    for r in records:
        if r["type"] != ev.SERVE_WINDOW:
            continue
        p = r["payload"]
        tid = f"{p['job_type']}:{p['index']}"
        stats = p.get("stats") or {}
        tenants = stats.get("tenants") or {}
        rates = {name: float(t.get("tokens_per_s", 0.0))
                 for name, t in tenants.items() if isinstance(t, dict)}
        prev = last.get(tid)
        if prev is not None:
            dt = max(0.0, float(r["timestamp"]) - prev[0])
            for name, rate in prev[1].items():
                tokens[name] = tokens.get(name, 0.0) + rate * dt
        last[tid] = (float(r["timestamp"]), rates)
    out: Dict[str, Dict[str, float]] = {}
    for name in sorted(tokens):
        w = float(weights.get(name, 1.0))
        out[name] = {"tokens": tokens[name], "weight": w,
                     "billed": tokens[name] * w}
    return out


def job_detail(job: Dict[str, Any]) -> Dict[str, Any]:
    """Parsed view of one job: metadata, final status, per-task rows, events
    (reference: JobDetailPageController's model assembly)."""
    records = ev.read_events(job["path"])
    meta = job.get("metadata") or {}
    final = next((r["payload"] for r in records
                  if r["type"] == ev.APPLICATION_FINISHED), {})
    tasks = [dict(r["payload"], timestamp=r["timestamp"])
             for r in records if r["type"] == ev.TASK_FINISHED]
    # Per-task metrics timeline from the TASK_METRICS samples (reference:
    # the portal's per-task metrics pages over the MetricsRpc history).
    timelines: Dict[str, List[Dict[str, Any]]] = {}
    for r in records:
        if r["type"] == ev.TASK_METRICS:
            p = r["payload"]
            tid = f"{p['job_type']}:{p['index']}"
            timelines.setdefault(tid, []).append(
                {"timestamp": r["timestamp"], **(p.get("metrics") or {})})
    timelines = {tid: _downsample(samples)
                 for tid, samples in timelines.items()}
    # History plane (PR 18): serve latency windows, train step costs,
    # and the autoscaler's self-verifying decision records — all read
    # from the SAME jhist, zero extra collection hooks.
    serve_windows: Dict[str, List[Dict[str, Any]]] = {}
    train_steps: Dict[str, List[Dict[str, Any]]] = {}
    scale_decisions: List[Dict[str, Any]] = []
    for r in records:
        p = r["payload"]
        if r["type"] == ev.SERVE_WINDOW:
            tid = f"{p['job_type']}:{p['index']}"
            serve_windows.setdefault(tid, []).append(
                {"timestamp": r["timestamp"], **(p.get("stats") or {})})
        elif r["type"] == ev.TRAIN_STEP:
            tid = f"{p['job_type']}:{p['index']}"
            train_steps.setdefault(tid, []).append(
                {"timestamp": r["timestamp"],
                 **{k: v for k, v in p.items()
                    if k not in ("job_type", "index")}})
        elif r["type"] == ev.SCALE_DECISION:
            scale_decisions.append(dict(p, timestamp=r["timestamp"]))
    # Elastic resize timeline (PR 19): one record per lifecycle phase
    # (DRAINING / RE-GANG / RESTORING, or DEGRADED) — rendered as the
    # recovery timeline so an operator can see exactly where a
    # preemption's wall time went.
    resizes = [dict(r["payload"], timestamp=r["timestamp"])
               for r in records if r["type"] == ev.RESIZE]
    # Continuous publication timeline (PR 20): PUBLISH (a new manifest
    # pointer became the fleet target) interleaved with the per-replica
    # SWAP outcomes — together they reconstruct which version each
    # replica served when, and what every swap window cost.
    publications = [dict(r["payload"], timestamp=r["timestamp"])
                    for r in records if r["type"] == ev.PUBLISH]
    swaps = [dict(r["payload"], timestamp=r["timestamp"])
             for r in records if r["type"] == ev.SWAP]
    serve_windows = {tid: _downsample(s) for tid, s in serve_windows.items()}
    train_steps = {tid: _downsample(s) for tid, s in train_steps.items()}
    # Per-tenant SLO rollup from each task's NEWEST window (qps/queued/
    # blocks are instantaneous — summed across tasks; p99 is the fleet
    # worst; completed is a counter — summed).
    tenant_slo: Dict[str, Dict[str, float]] = {}
    for tid, samples in serve_windows.items():
        last = samples[-1]
        tenants = last.get("tenants") or {}
        if not isinstance(tenants, dict):
            continue
        for name, t in tenants.items():
            if not isinstance(t, dict):
                continue
            agg = tenant_slo.setdefault(name, {
                "qps": 0.0, "tokens_per_s": 0.0, "p99_ms": 0.0,
                "queued": 0.0, "blocks": 0.0, "completed": 0.0})
            for k in ("qps", "tokens_per_s", "queued", "blocks",
                      "completed"):
                agg[k] += float(t.get(k, 0.0))
            agg["p99_ms"] = max(agg["p99_ms"], float(t.get("p99_ms", 0.0)))
    # Replay verdicts: the load-bearing check — each SCALE_DECISION
    # recomputed from its own logged inputs must match the live delta.
    scale_replay: List[Dict[str, Any]] = []
    if scale_decisions:
        from tony_tpu.serve import scaling
        try:
            scale_replay = scaling.replay_decisions(scale_decisions)
        except (KeyError, TypeError, ValueError):
            scale_replay = []       # pre-PR-18 or truncated records
    all_running = next((r for r in records
                        if r["type"] == ev.ALL_TASKS_RUNNING), None)
    # Collected profiler traces live next to the jhist tree:
    # <history>/traces/<app_id>/<task>/... (SURVEY.md §5.1 collection half).
    from tony_tpu.profiler import list_traces
    history_root = Path(job["path"]).parent.parent
    return {
        "app_id": job["app_id"],
        "state": job["state"],
        "metadata": meta,
        "final": final,
        "tasks": tasks,
        "metrics_timelines": timelines,
        "serve_windows": serve_windows,
        "train_steps": train_steps,
        "tenant_slo": tenant_slo,
        "billing": billing_rollup(records, meta.get("config")),
        "resizes": resizes,
        "publications": publications,
        "swaps": swaps,
        "scale_decisions": scale_decisions,
        "scale_replay": scale_replay,
        "traces": list_traces(history_root, job["app_id"]),
        "submit_to_running_s": (all_running or {}).get(
            "payload", {}).get("submit_to_running_s"),
        "events": records,
    }


# ---------------------------------------------------------------------------
# Terminal rendering (tony history list / show)
# ---------------------------------------------------------------------------

def render_list(jobs: List[Dict[str, Any]]) -> str:
    if not jobs:
        return "no jobs found"
    lines = [f"{'APP ID':<28} {'STATE':<9} {'USER':<10} {'NAME':<24} STARTED"]
    for job in jobs:
        m = job.get("metadata") or {}
        started = m.get("started")
        when = (time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(started))
                if started else "-")
        lines.append(f"{job['app_id']:<28} {job['state']:<9} "
                     f"{m.get('user', '-'):<10} {m.get('app_name', '-'):<24} "
                     f"{when}")
    return "\n".join(lines)


def render_show(detail: Dict[str, Any]) -> str:
    out = [f"application {detail['app_id']} [{detail['state']}]"]
    final = detail["final"]
    if final:
        out.append(f"  status: {final.get('status')}"
                   + (f" — {final['message']}" if final.get("message") else ""))
    if detail.get("submit_to_running_s"):
        out.append(f"  submit→all-running: "
                   f"{detail['submit_to_running_s']:.2f}s")
    m = detail["metadata"]
    if m:
        out.append(f"  user: {m.get('user')}  name: {m.get('app_name')}")
    if detail["tasks"]:
        out.append("  tasks:")
        for t in detail["tasks"]:
            metrics = t.get("metrics") or {}
            mstr = (" " + " ".join(f"{k}={v}" for k, v in sorted(
                metrics.items()))) if metrics else ""
            out.append(f"    {t['job_type']}:{t['index']} {t['status']} "
                       f"exit={t.get('exit_code')}{mstr}"
                       + (f" — {t['diagnostics']}" if t.get("diagnostics") else ""))
    if detail.get("tenant_slo"):
        out.append("  tenant SLO (latest window, fleet rollup):")
        for name, t in sorted(detail["tenant_slo"].items()):
            out.append(f"    {name}: p99={t['p99_ms']:.1f}ms "
                       f"qps={t['qps']:.2f} tok/s={t['tokens_per_s']:.1f} "
                       f"queued={t['queued']:.0f} blocks={t['blocks']:.0f} "
                       f"completed={t['completed']:.0f}")
    if detail.get("serve_windows"):
        out.append("  serve windows:")
        for tid, samples in sorted(detail["serve_windows"].items()):
            last = samples[-1]
            out.append(f"    {tid}: {len(samples)} window(s), last "
                       f"p99={float(last.get('p99_ms', 0.0)):.1f}ms "
                       f"qps={float(last.get('qps', 0.0)):.2f} "
                       f"queue={float(last.get('queue_depth', 0.0)):.0f} "
                       f"rejected="
                       f"{float(last.get('admission_rejections', 0.0)):.0f}")
    if detail.get("train_steps"):
        out.append("  train steps:")
        for tid, samples in sorted(detail["train_steps"].items()):
            last = samples[-1]
            mean_t = sum(float(s.get("step_time_s", 0.0))
                         for s in samples) / len(samples)
            out.append(f"    {tid}: {len(samples)} step(s), mean "
                       f"{mean_t * 1e3:.1f}ms/step, last "
                       f"step={int(last.get('step', 0))} "
                       f"mfu={float(last.get('mfu', 0.0)):.3f} "
                       f"coll={float(last.get('collective_bytes', 0.0)):.0f}B")
    if detail.get("resizes"):
        out.append("  resize timeline:")
        for p in detail["resizes"]:
            when = time.strftime("%H:%M:%S", time.localtime(p["timestamp"]))
            mark = "ok" if p.get("ok") else "FAILED"
            out.append(f"    {when} {p.get('phase')} "
                       f"[{p.get('trigger')}] {p.get('job_type')} "
                       f"{p.get('old_workers')}→{p.get('new_workers')} "
                       f"{float(p.get('wall_s', 0.0)):.2f}s [{mark}]"
                       + (f" — {p['detail']}" if p.get("detail") else ""))
    if detail.get("publications") or detail.get("swaps"):
        out.append("  publication timeline:")
        merged = sorted(
            [("PUBLISH", p) for p in detail.get("publications", [])]
            + [("SWAP", p) for p in detail.get("swaps", [])],
            key=lambda kp: kp[1]["timestamp"])
        for kind, p in merged:
            when = time.strftime("%H:%M:%S", time.localtime(p["timestamp"]))
            if kind == "PUBLISH":
                out.append(f"    {when} PUBLISH v{p.get('version')} "
                           f"(step {p.get('step')})"
                           + (f" — {p['note']}" if p.get("note") else ""))
            else:
                mark = "ok" if p.get("ok") else "FAILED"
                out.append(f"    {when} SWAP {p.get('job_type')}:"
                           f"{p.get('index')} "
                           f"v{p.get('from_version')}→v{p.get('to_version')} "
                           f"(step {p.get('step')}) "
                           f"{float(p.get('wall_s', 0.0)):.2f}s [{mark}]"
                           + (f" — {p['detail']}" if p.get("detail") else ""))
    if detail.get("billing"):
        out.append("  billing (tokens × weight, integrated over windows):")
        for name, b in sorted(detail["billing"].items()):
            out.append(f"    {name}: tokens={b['tokens']:.0f} "
                       f"weight={b['weight']:g} billed={b['billed']:.0f}")
    if detail.get("scale_replay"):
        ok = sum(1 for v in detail["scale_replay"] if v["match"])
        out.append(f"  scale decisions ({ok}/{len(detail['scale_replay'])} "
                   f"replay exactly):")
        for p, v in zip(detail["scale_decisions"], detail["scale_replay"]):
            when = time.strftime("%H:%M:%S", time.localtime(p["timestamp"]))
            mark = "ok" if v["match"] else f"MISMATCH(replay={v['replayed']})"
            out.append(f"    {when} {p.get('job_type')}: delta="
                       f"{p.get('delta'):+d} active={p.get('n_active')} "
                       f"[{mark}]")
    if detail.get("traces"):
        out.append("  traces:")
        for tid, files in sorted(detail["traces"].items()):
            total = sum(f["bytes"] for f in files)
            out.append(f"    {tid}: {len(files)} file(s), {total} bytes")
    out.append("  events:")
    for r in detail["events"]:
        when = time.strftime("%H:%M:%S", time.localtime(r["timestamp"]))
        out.append(f"    {when} {r['type']}")
    return "\n".join(out)


def parse_when(s: Optional[str]) -> Optional[float]:
    """``--since``/``--until`` value → epoch seconds: raw epoch floats
    pass through; otherwise local-time ``YYYY-MM-DD`` or ``YYYY-MM-DD
    HH:MM:SS`` (the formats the list/show renderers print, so a window
    can be copied straight off their output). None/empty → None."""
    if not s:
        return None
    try:
        return float(s)
    except ValueError:
        pass
    for fmt in ("%Y-%m-%d %H:%M:%S", "%Y-%m-%dT%H:%M:%S", "%Y-%m-%d"):
        try:
            return time.mktime(time.strptime(s, fmt))
        except ValueError:
            continue
    raise ValueError(f"unparseable time {s!r} (want epoch seconds, "
                     f"YYYY-MM-DD or 'YYYY-MM-DD HH:MM:SS')")


def bill_rows(jobs: List[Dict[str, Any]], tenant: Optional[str] = None, *,
              since: Optional[float] = None,
              until: Optional[float] = None) -> List[Dict[str, Any]]:
    """The billing statement's structured rows — one per (job, tenant).
    ``since``/``until`` (epoch seconds) clip the SERVE_WINDOW ledger to
    a billing window BEFORE the rollup integrates it, so a monthly
    statement bills only that month's tokens however long the job
    ran."""
    rows: List[Dict[str, Any]] = []
    for job in jobs:
        records = ev.read_events(job["path"])
        if since is not None or until is not None:
            records = [
                r for r in records
                if (since is None or r.get("timestamp", 0.0) >= since)
                and (until is None or r.get("timestamp", 0.0) <= until)]
        meta = job.get("metadata") or {}
        for name, b in billing_rollup(records, meta.get("config")).items():
            if tenant is not None and name != tenant:
                continue
            rows.append({"app_id": job["app_id"], "tenant": name,
                         "tokens": b["tokens"], "weight": b["weight"],
                         "billed": b["billed"]})
    return rows


def render_bill(jobs: List[Dict[str, Any]],
                tenant: Optional[str] = None, *,
                since: Optional[float] = None,
                until: Optional[float] = None) -> str:
    """Cross-job billing statement for one tenant (or all tenants when
    ``tenant`` is None): each job's reader-side rollup, then the grand
    total. Pure jhist read — no AM involvement, so it works on finished
    and running jobs alike."""
    rows = bill_rows(jobs, tenant, since=since, until=until)
    who = tenant if tenant is not None else "any tenant"
    if not rows:
        return f"no serve-window ledgers found for {who}"
    out = [f"{'APP ID':<28} {'TENANT':<10} {'TOKENS':>12} "
           f"{'WEIGHT':>7} {'BILLED':>12}"]
    for r in rows:
        out.append(f"{r['app_id']:<28} {r['tenant']:<10} "
                   f"{r['tokens']:>12.0f} {r['weight']:>7g} "
                   f"{r['billed']:>12.0f}")
    total = sum(r["billed"] for r in rows)
    out.append(f"{'TOTAL':<28} {'':<10} {'':>12} {'':>7} {total:>12.0f}")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# HTTP portal (tony history serve) — reference: the Play web app
# ---------------------------------------------------------------------------

_PAGE = """<!doctype html><html><head><title>{title}</title><style>
body{{font-family:sans-serif;margin:2em}}table{{border-collapse:collapse}}
td,th{{border:1px solid #ccc;padding:4px 10px;text-align:left}}
th{{background:#f0f0f0}}a{{text-decoration:none}}
.ok{{color:#070}}.bad{{color:#b00}}</style></head>
<body><h2>{title}</h2>{body}</body></html>"""


def _jobs_page(jobs: List[Dict[str, Any]]) -> str:
    rows = []
    for job in jobs:
        m = job.get("metadata") or {}
        started = m.get("started")
        when = (time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(started))
                if started else "-")
        rows.append(
            f"<tr><td><a href='/jobs/{html.escape(job['app_id'])}'>"
            f"{html.escape(job['app_id'])}</a></td>"
            f"<td>{html.escape(job['state'])}</td>"
            f"<td>{html.escape(str(m.get('user', '-')))}</td>"
            f"<td>{html.escape(str(m.get('app_name', '-')))}</td>"
            f"<td>{when}</td></tr>")
    body = ("<table><tr><th>app id</th><th>state</th><th>user</th>"
            "<th>name</th><th>started</th></tr>" + "".join(rows) + "</table>")
    return _PAGE.format(title="TonY-TPU jobs", body=body)


def _job_page(detail: Dict[str, Any]) -> str:
    final = detail["final"]
    status = final.get("status", detail["state"])
    cls = "ok" if status == "SUCCEEDED" else "bad"
    parts = [f"<p>status: <b class='{cls}'>{html.escape(str(status))}</b>"]
    if final.get("message"):
        parts.append(f" — {html.escape(final['message'])}")
    parts.append("</p><h3>Tasks</h3><table><tr><th>task</th><th>status</th>"
                 "<th>exit</th><th>metrics</th><th>diagnostics</th></tr>")
    for t in detail["tasks"]:
        metrics = ", ".join(f"{k}={v}" for k, v in sorted(
            (t.get("metrics") or {}).items()))
        parts.append(
            f"<tr><td>{html.escape(t['job_type'])}:{t['index']}</td>"
            f"<td>{html.escape(t['status'])}</td>"
            f"<td>{t.get('exit_code')}</td><td>{html.escape(metrics)}</td>"
            f"<td>{html.escape(t.get('diagnostics') or '')}</td></tr>")
    parts.append("</table>")
    if detail.get("submit_to_running_s"):
        parts.append(f"<p>submit→all-running: "
                     f"{detail['submit_to_running_s']:.2f}s</p>")
    if detail.get("metrics_timelines"):
        parts.append("<h3>Metrics timeline</h3>")
        for tid, samples in sorted(detail["metrics_timelines"].items()):
            parts.append(f"<h4>{html.escape(tid)} "
                         f"({len(samples)} samples)</h4>"
                         "<table><tr><th>time</th><th>metrics</th></tr>")
            for s in samples:
                when = time.strftime("%H:%M:%S",
                                     time.localtime(s["timestamp"]))
                vals = ", ".join(f"{k}={v}" for k, v in sorted(s.items())
                                 if k != "timestamp")
                parts.append(f"<tr><td>{when}</td>"
                             f"<td>{html.escape(vals)}</td></tr>")
            parts.append("</table>")
    if detail.get("tenant_slo"):
        parts.append("<h3>Tenant SLO dashboard</h3><table><tr>"
                     "<th>tenant</th><th>p99 ms</th><th>qps</th>"
                     "<th>tok/s</th><th>queued</th><th>blocks</th>"
                     "<th>completed</th></tr>")
        for name, t in sorted(detail["tenant_slo"].items()):
            parts.append(
                f"<tr><td>{html.escape(name)}</td>"
                f"<td>{t['p99_ms']:.1f}</td><td>{t['qps']:.2f}</td>"
                f"<td>{t['tokens_per_s']:.1f}</td>"
                f"<td>{t['queued']:.0f}</td><td>{t['blocks']:.0f}</td>"
                f"<td>{t['completed']:.0f}</td></tr>")
        parts.append("</table>")
    if detail.get("serve_windows"):
        parts.append("<h3>Serve latency windows</h3>")
        for tid, samples in sorted(detail["serve_windows"].items()):
            parts.append(f"<h4>{html.escape(tid)} ({len(samples)} "
                         f"windows)</h4><table><tr><th>time</th>"
                         "<th>qps</th><th>p99 ms</th><th>queue</th>"
                         "<th>rejected</th><th>deferred</th></tr>")
            for s in samples:
                when = time.strftime("%H:%M:%S",
                                     time.localtime(s["timestamp"]))
                parts.append(
                    f"<tr><td>{when}</td>"
                    f"<td>{float(s.get('qps', 0.0)):.2f}</td>"
                    f"<td>{float(s.get('p99_ms', 0.0)):.1f}</td>"
                    f"<td>{float(s.get('queue_depth', 0.0)):.0f}</td>"
                    f"<td>{float(s.get('admission_rejections', 0.0)):.0f}"
                    f"</td>"
                    f"<td>{float(s.get('qos_deferrals', 0.0)):.0f}</td>"
                    f"</tr>")
            parts.append("</table>")
    if detail.get("train_steps"):
        parts.append("<h3>Train step trend</h3>")
        for tid, samples in sorted(detail["train_steps"].items()):
            parts.append(f"<h4>{html.escape(tid)} ({len(samples)} "
                         f"steps)</h4><table><tr><th>time</th>"
                         "<th>step</th><th>step ms</th>"
                         "<th>collective B</th><th>MFU</th></tr>")
            for s in samples:
                when = time.strftime("%H:%M:%S",
                                     time.localtime(s["timestamp"]))
                parts.append(
                    f"<tr><td>{when}</td><td>{int(s.get('step', 0))}</td>"
                    f"<td>{float(s.get('step_time_s', 0.0)) * 1e3:.1f}</td>"
                    f"<td>{float(s.get('collective_bytes', 0.0)):.0f}</td>"
                    f"<td>{float(s.get('mfu', 0.0)):.3f}</td></tr>")
            parts.append("</table>")
    if detail.get("resizes"):
        parts.append("<h3>Resize timeline</h3><table><tr><th>time</th>"
                     "<th>phase</th><th>trigger</th><th>gang</th>"
                     "<th>workers</th><th>wall s</th><th>ok</th>"
                     "<th>detail</th></tr>")
        for p in detail["resizes"]:
            when = time.strftime("%H:%M:%S", time.localtime(p["timestamp"]))
            mark = ("<b class='ok'>ok</b>" if p.get("ok")
                    else "<b class='bad'>failed</b>")
            parts.append(
                f"<tr><td>{when}</td>"
                f"<td>{html.escape(str(p.get('phase')))}</td>"
                f"<td>{html.escape(str(p.get('trigger')))}</td>"
                f"<td>{html.escape(str(p.get('job_type')))}</td>"
                f"<td>{p.get('old_workers')}&rarr;{p.get('new_workers')}"
                f"</td><td>{float(p.get('wall_s', 0.0)):.2f}</td>"
                f"<td>{mark}</td>"
                f"<td>{html.escape(str(p.get('detail') or ''))}</td></tr>")
        parts.append("</table>")
    if detail.get("publications") or detail.get("swaps"):
        parts.append("<h3>Publication timeline</h3><table><tr>"
                     "<th>time</th><th>event</th><th>who</th>"
                     "<th>version</th><th>step</th><th>wall s</th>"
                     "<th>ok</th><th>detail</th></tr>")
        merged = sorted(
            [("PUBLISH", p) for p in detail.get("publications", [])]
            + [("SWAP", p) for p in detail.get("swaps", [])],
            key=lambda kp: kp[1]["timestamp"])
        for kind, p in merged:
            when = time.strftime("%H:%M:%S", time.localtime(p["timestamp"]))
            if kind == "PUBLISH":
                parts.append(
                    f"<tr><td>{when}</td><td>PUBLISH</td><td>train</td>"
                    f"<td>v{p.get('version')}</td><td>{p.get('step')}</td>"
                    f"<td></td><td></td>"
                    f"<td>{html.escape(str(p.get('note') or ''))}</td></tr>")
            else:
                mark = ("<b class='ok'>ok</b>" if p.get("ok")
                        else "<b class='bad'>failed</b>")
                parts.append(
                    f"<tr><td>{when}</td><td>SWAP</td>"
                    f"<td>{html.escape(str(p.get('job_type')))}:"
                    f"{p.get('index')}</td>"
                    f"<td>v{p.get('from_version')}&rarr;"
                    f"v{p.get('to_version')}</td><td>{p.get('step')}</td>"
                    f"<td>{float(p.get('wall_s', 0.0)):.2f}</td>"
                    f"<td>{mark}</td>"
                    f"<td>{html.escape(str(p.get('detail') or ''))}</td>"
                    f"</tr>")
        parts.append("</table>")
    if detail.get("billing"):
        parts.append("<h3>Billing</h3><table><tr><th>tenant</th>"
                     "<th>tokens</th><th>weight</th><th>billed</th></tr>")
        for name, b in sorted(detail["billing"].items()):
            parts.append(
                f"<tr><td>{html.escape(name)}</td>"
                f"<td>{b['tokens']:.0f}</td><td>{b['weight']:g}</td>"
                f"<td>{b['billed']:.0f}</td></tr>")
        parts.append("</table>")
    if detail.get("scale_replay"):
        parts.append("<h3>Autoscale decisions (replayed)</h3><table><tr>"
                     "<th>time</th><th>gang</th><th>delta</th>"
                     "<th>active</th><th>replay</th></tr>")
        for p, v in zip(detail["scale_decisions"], detail["scale_replay"]):
            when = time.strftime("%H:%M:%S", time.localtime(p["timestamp"]))
            if v["match"]:
                verdict = "<b class='ok'>match</b>"
            else:
                verdict = (f"<b class='bad'>mismatch "
                           f"(replay={v['replayed']})</b>")
            parts.append(
                f"<tr><td>{when}</td>"
                f"<td>{html.escape(str(p.get('job_type')))}</td>"
                f"<td>{p.get('delta'):+d}</td><td>{p.get('n_active')}</td>"
                f"<td>{verdict}</td></tr>")
        parts.append("</table>")
    if detail.get("traces"):
        parts.append("<h3>Profiler traces</h3><table><tr><th>task</th>"
                     "<th>file</th><th>bytes</th></tr>")
        for tid, files in sorted(detail["traces"].items()):
            for f in files:
                parts.append(f"<tr><td>{html.escape(tid)}</td>"
                             f"<td><code>{html.escape(str(f['file']))}</code>"
                             f"</td><td>{f['bytes']}</td></tr>")
        parts.append("</table><p>open with: <code>tensorboard --logdir "
                     "&lt;history&gt;/traces/"
                     + html.escape(detail['app_id']) + "/&lt;task&gt;</code>"
                     "</p>")
    parts.append("<h3>Events</h3><table><tr><th>time</th>"
                 "<th>type</th><th>payload</th></tr>")
    for r in detail["events"]:
        when = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(r["timestamp"]))
        payload = html.escape(json.dumps(r["payload"], sort_keys=True)[:400])
        parts.append(f"<tr><td>{when}</td><td>{html.escape(r['type'])}</td>"
                     f"<td><code>{payload}</code></td></tr>")
    parts.append("</table><h3>Config</h3><table><tr><th>key</th><th>value</th></tr>")
    for k, v in sorted((detail["metadata"].get("config") or {}).items()):
        parts.append(f"<tr><td>{html.escape(k)}</td>"
                     f"<td>{html.escape(str(v))}</td></tr>")
    parts.append("</table><p><a href='/'>← all jobs</a></p>")
    return _PAGE.format(title=f"Job {html.escape(detail['app_id'])}",
                        body="".join(parts))


class HistoryServer:
    """Tiny threaded HTTP portal over a history root."""

    def __init__(self, history_dir: Optional[str | Path],
                 host: str = "127.0.0.1", port: int = 19885):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: str,
                      ctype: str = "text/html; charset=utf-8") -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self) -> None:
                try:
                    if self.path in ("/", "/jobs"):
                        self._send(200, _jobs_page(gather_jobs(outer.history_dir)))
                    elif self.path.startswith("/jobs/"):
                        app_id = self.path[len("/jobs/"):]
                        job = find_job(app_id, outer.history_dir)
                        if job is None:
                            self._send(404, _PAGE.format(
                                title="Not found",
                                body=f"<p>no job {html.escape(app_id)}</p>"))
                        else:
                            self._send(200, _job_page(job_detail(job)))
                    elif self.path == "/api/jobs":
                        self._send(200, json.dumps(
                            gather_jobs(outer.history_dir), default=str),
                            "application/json")
                    else:
                        self._send(404, _PAGE.format(
                            title="Not found", body="<p>404</p>"))
                except BrokenPipeError:
                    pass

        self.history_dir = Path(history_dir) if history_dir else None
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def main(args) -> int:
    """CLI entry (``tony history ...``)."""
    history_dir = getattr(args, "history_dir", None)
    if args.action == "list":
        print(render_list(gather_jobs(history_dir)))
        return 0
    if args.action == "show":
        if not args.app_id:
            print("usage: tony history show <app_id>")
            return 2
        job = find_job(args.app_id, history_dir)
        if job is None:
            print(f"no job {args.app_id} found")
            return 1
        print(render_show(job_detail(job)))
        return 0
    if args.action == "bill":
        # The app_id positional doubles as the tenant name: `tony
        # history bill gold` rolls up gold's billed tokens across every
        # job the history scan can see; with no tenant, all tenants.
        try:
            since = parse_when(getattr(args, "since", None))
            until = parse_when(getattr(args, "until", None))
        except ValueError as e:
            print(f"tony history bill: {e}")
            return 2
        jobs = gather_jobs(history_dir)
        tenant = args.app_id or None
        if getattr(args, "json", False):
            print(json.dumps(bill_rows(jobs, tenant, since=since,
                                       until=until),
                             indent=2, sort_keys=True))
        elif getattr(args, "csv", False):
            rows = bill_rows(jobs, tenant, since=since, until=until)
            print("app_id,tenant,tokens,weight,billed")
            for r in rows:
                print(f"{r['app_id']},{r['tenant']},{r['tokens']:.0f},"
                      f"{r['weight']:g},{r['billed']:.0f}")
        else:
            print(render_bill(jobs, tenant, since=since, until=until))
        return 0
    if args.action == "serve":
        # Loopback by default: jhist pages expose full job configs; binding
        # wider is an explicit opt-in (--bind 0.0.0.0).
        server = HistoryServer(history_dir, host=getattr(
            args, "bind", "127.0.0.1") or "127.0.0.1", port=args.port)
        print(f"history portal at http://127.0.0.1:{server.port}/")
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            server.shutdown()
        return 0
    return 2
