"""Training harness: sharded state, train steps, multi-host data feeding.

The reference has no training loop of its own — user scripts train inside
whatever framework TonY launched (SURVEY.md §1 L7). The TPU rebuild makes the
loop a library so examples and benchmarks share one GSPMD path:

* :func:`create_train_state` — init params under ``jit`` with shardings
  resolved from the model's flax logical axis names through
  :data:`tony_tpu.parallel.RULES` (optimizer state inherits by propagation);
* :func:`make_train_step` — one jitted step: loss → grad → update, batch
  sharded over the DP axes; XLA inserts the gradient ``psum`` over ICI
  (this IS the Horovod-allreduce/DDP replacement, SURVEY.md §2.3–2.4);
* :func:`global_batch` — multi-host feeding: each process contributes its
  local shard of the global batch (``jax.make_array_from_process_local_data``),
  the executor-side analogue of per-worker data sharding.
"""

from __future__ import annotations

import logging
import os
import weakref
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax.training.train_state import TrainState
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tony_tpu import chaos, constants
from tony_tpu import parallel as par
from tony_tpu.compat import mesh_context
from tony_tpu.parallel import overlap

_log = logging.getLogger(__name__)


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross entropy; labels are integer classes (any rank —
    tokens or images)."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels).mean()


def next_token_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Causal-LM loss: predict token t+1 from position t."""
    return cross_entropy_loss(logits[:, :-1], tokens[:, 1:])


def chunked_next_token_xent(hidden: jax.Array, lm_head: jax.Array,
                            tokens: jax.Array, chunk: int,
                            dtype=jnp.bfloat16) -> jax.Array:
    """Fused LM-head + causal cross entropy without materializing the
    [B, T, V] logits (f32: 4 GB at b64·s512·v32k — the tensor that capped
    the bench batch at 32). Rows are processed in ``chunk``-sized scan
    steps: per-chunk bf16 logits on the MXU, f32 logsumexp − label logit,
    summed into a carry; ``jax.checkpoint`` on the body recomputes the
    chunk logits in the backward instead of stacking them as residuals
    (which would rebuild the full tensor)."""
    d = hidden.shape[-1]
    rows = hidden[:, :-1].reshape(-1, d)
    labels = tokens[:, 1:].reshape(-1)
    r = rows.shape[0]
    n = -(-r // chunk)   # ceil: minimal whole-chunk cover
    pad = n * chunk - r
    if pad:
        # Pad to a whole number of chunks; padded rows get weight 0.
        rows = jnp.pad(rows, ((0, pad), (0, 0)))
        labels = jnp.pad(labels, (0, pad))
        weights = jnp.pad(jnp.ones((r,), jnp.float32), (0, pad))
    else:
        weights = jnp.ones((r,), jnp.float32)
    wb = lm_head.astype(dtype)

    @jax.checkpoint
    def body(acc, xs):
        hc, lc, mc = xs
        logits = (hc @ wb).astype(jnp.float32)          # [chunk, V]
        lse = jax.nn.logsumexp(logits, axis=-1)
        lab = jnp.take_along_axis(logits, lc[:, None], axis=-1)[:, 0]
        return acc + ((lse - lab) * mc).sum(), None

    total, _ = jax.lax.scan(
        body, jnp.float32(0.0),
        (rows.reshape(n, chunk, d), labels.reshape(n, chunk),
         weights.reshape(n, chunk)))
    return total / r


def param_shardings(model: nn.Module, sample_input: jax.Array, mesh: Mesh,
                    rng: Optional[jax.Array] = None,
                    rules=par.RULES) -> Tuple[Any, Any]:
    """(abstract params, NamedSharding tree) from the model's logical axis
    metadata — no real initialization happens (eval_shape only)."""
    rng = jax.random.PRNGKey(0) if rng is None else rng
    with nn.logical_axis_rules(rules):
        abstract = jax.eval_shape(model.init, rng, sample_input)
    logical = nn.get_partition_spec(abstract)
    shardings = nn.logical_to_mesh_sharding(logical, mesh, list(rules))
    return abstract["params"], shardings["params"]


def create_train_state(model: nn.Module, tx: Any,
                       sample_input: jax.Array, rng: jax.Array,
                       mesh: Optional[Mesh] = None,
                       rules=par.RULES) -> TrainState:
    """Initialize a TrainState; with a mesh, params are created already
    sharded (jit + constraints — no host-memory detour) and the optimizer
    state inherits the layout via GSPMD propagation.

    ``tx`` may be an optax ``GradientTransformation`` (leaf-major state,
    the default path) or a :class:`tony_tpu.ops.fused_optim
    .FusedOptimizer` — then the optimizer state is **bucket-resident**:
    per-bucket f32 moment buffers in the ZeRO-3 scatter layout, planned
    from the params' committed shardings, consumed in place by
    ``make_accum_train_step(update="fused_bucket")``."""
    from tony_tpu.ops import fused_optim

    fused = isinstance(tx, fused_optim.FusedOptimizer)
    if mesh is None:
        params = nn.unbox(model.init(rng, sample_input))["params"]
        if fused:
            return TrainState(step=0, apply_fn=model.apply, params=params,
                              tx=tx, opt_state=tx.init_state(params))
        return TrainState.create(apply_fn=model.apply, params=params, tx=tx)
    _, shardings = param_shardings(model, sample_input, mesh, rng, rules)

    def make(rng):
        with nn.logical_axis_rules(rules):
            params = nn.unbox(model.init(rng, sample_input))["params"]
        params = jax.tree.map(jax.lax.with_sharding_constraint,
                              params, shardings)
        if fused:
            return params
        return TrainState.create(apply_fn=model.apply, params=params, tx=tx)

    with mesh_context(mesh):
        out = jax.jit(make)(rng)
    if not fused:
        return out
    # Bucket planning reads COMMITTED shardings, so the opt state is
    # built eagerly from the real (already-sharded) params.
    return TrainState(step=0, apply_fn=model.apply, params=out, tx=tx,
                      opt_state=tx.init_state(out, mesh))


def make_train_step(loss_of: Callable[[jax.Array, Dict[str, jax.Array]],
                                      jax.Array] = None,
                    mesh: Optional[Mesh] = None,
                    rules=par.RULES,
                    donate: bool = True,
                    seq_axis: bool = False,
                    apply_kwargs_of: Optional[Callable[
                        [Dict[str, jax.Array]], Dict[str, Any]]] = None):
    """Build the jitted train step ``(state, batch) -> (state, metrics)``.

    ``loss_of(logits, batch)`` defaults to classification cross entropy on
    ``batch={'x', 'y'}``. With a mesh, the batch is constrained onto the DP
    axes so GSPMD shards compute and allreduces grads over ICI;
    ``seq_axis=True`` additionally keeps the sequence dim on the ring axis
    — long-context batches fed via ``global_batch(..., seq_axis=True)``
    were being re-constrained OFF the ring axis inside the step before
    this kwarg existed. ``apply_kwargs_of(batch)`` feeds extra kwargs to
    the model (e.g. ``targets`` for a model with a fused head+loss —
    ``loss_of`` then receives the model's scalar loss as its first
    argument).
    """
    if loss_of is None:
        loss_of = lambda logits, batch: cross_entropy_loss(logits, batch["y"])

    def step(state: TrainState, batch: Dict[str, jax.Array]):
        if mesh is not None:
            batch = jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(
                    # The (batch, seq) spec is rank-2: rank-1 leaves
                    # (labels, weights) take the plain batch sharding.
                    x, par.batch_sharding(
                        mesh, seq_axis=seq_axis and x.ndim >= 2)), batch)

        def loss_fn(params):
            extra = apply_kwargs_of(batch) if apply_kwargs_of else {}
            with nn.logical_axis_rules(rules):
                # mutable="losses": models that sow auxiliary objectives
                # (e.g. the MoE load-balancing loss) contribute them here;
                # dense models return an empty collection.
                logits, sown = state.apply_fn(
                    {"params": params}, batch["x"], mutable="losses",
                    **extra)
            aux = sum((leaf.sum() for leaf in
                       jax.tree.leaves(sown.get("losses", {}))),
                      start=jnp.float32(0.0))
            return loss_of(logits, batch) + aux, aux

        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        new_state = state.apply_gradients(grads=grads)
        gnorm = optax.global_norm(grads)
        return new_state, {"loss": loss, "grad_norm": gnorm,
                           "aux_loss": aux}

    jitted = jax.jit(step, donate_argnums=(0,) if donate else ())
    if mesh is None:
        return jitted

    def stepper(state, batch):
        with mesh_context(mesh):
            return jitted(state, batch)
    return stepper


def make_accum_train_step(loss_of: Callable[[jax.Array,
                                             Dict[str, jax.Array]],
                                            jax.Array] = None,
                          mesh: Mesh = None,
                          *,
                          microbatches: int,
                          bucket_bytes: int = overlap.DEFAULT_BUCKET_BYTES,
                          reduce_op: str = "all_reduce",
                          hierarchy: str = "auto",
                          gather: str = "bucketed",
                          prefetch: int = 1,
                          update: str = "optax",
                          quant: bool = False,
                          donate: bool = True,
                          apply_kwargs_of: Optional[Callable[
                              [Dict[str, jax.Array]],
                              Dict[str, Any]]] = None,
                          aot_cache: Optional[Any] = None):
    """Microbatched-accumulation train step with bucketed gradient sync —
    the comm/compute-overlap counterpart of :func:`make_train_step`.

    Same ``(state, batch) -> (state, metrics)`` contract and numerics
    (loss/grads match the monolithic step to fp reassociation), but the
    local batch is split into ``microbatches`` inside one ``lax.scan`` and
    the gradient reduction is issued per size-targeted bucket as each
    microbatch's backward finishes —
    :func:`tony_tpu.parallel.overlap.microbatch_grads` is the engine;
    :func:`~tony_tpu.parallel.overlap.overlap_xla_flags` supplies the XLA
    knobs that turn the structure into actual overlap on TPU.

    The parameter layout is detected from the state's committed shardings
    per call (:func:`~tony_tpu.parallel.overlap.fsdp_param_specs`):

    * replicated params → the pure-DP path (grads replicated);
    * fsdp-sharded params (ZeRO-3, e.g. from ``create_train_state`` on an
      ``fsdp > 1`` mesh) → grads are ``psum_scatter``-ed straight into the
      shard layout and ``apply_gradients``/``global_norm`` run on sharded
      grads — replicated gradients never materialize. The forward param
      ``all_gather``s are bucketed + prefetched by the collective
      scheduler by default (``gather="bucketed"``, ``prefetch=k`` — see
      :class:`tony_tpu.parallel.sched.GatherPlan`); ``gather="per_leaf"``
      keeps the pre-scheduler path as the bit-exact numerics pin.

    On a multi-slice mesh (``MeshSpec(slices=...)``) the reduce is
    hierarchical by default: per-bucket ``psum_scatter`` over ICI, then a
    per-bucket DCN allreduce inside the scan (``hierarchy="flat"`` forces
    the single-level reduce — the numerics pin). The model must be
    collective-free inside (same contract as ``gpipe``'s ``stage_fn``).

    ``update`` selects the optimizer path: ``"optax"`` (default — the
    reduced grads unpack to leaves and ``state.apply_gradients`` runs
    optax's per-leaf update) or ``"fused_bucket"`` — the state's ``tx``
    must be a :class:`tony_tpu.ops.fused_optim.FusedOptimizer` and its
    opt state bucket-resident (``create_train_state`` builds it): the
    update then runs INSIDE the accum region as one fused kernel per
    bucket buffer, straight off the scan's reduce accumulators — grads
    never re-materialize as a leaf pytree, scatter buckets never leave
    the shard layout, and the reported ``grad_norm`` is the bucket-major
    fused reduction (per-leaf value up to fp reassociation). The bucket
    plan is the tx's (``bucket_bytes`` on the FusedOptimizer — the
    ``bucket_bytes`` argument here must agree, it sized the opt state).

    ``quant=True`` switches the ZeRO-3 forward param gathers to the
    quantized int8 wire format (:mod:`tony_tpu.ops.quant`): the state
    must be a :class:`~tony_tpu.ops.quant.QuantTrainState` (attach with
    ``quant.with_gather_quant``) whose delayed-scaling amax histories
    ride the step — f32 master params and the scatter-bucket gradient
    reduce are untouched; only the forward gather bytes shrink (4× for
    f32 params). Requires ``gather="bucketed"``; composes with both
    ``update`` modes. The loss-pin gate in ``tests/test_quant.py`` is
    the numerics contract for this knob.
    """
    if mesh is None:
        raise ValueError("make_accum_train_step requires a mesh: the "
                         "bucketed reduction IS the cross-device sync")
    if update not in ("optax", "fused_bucket"):
        raise ValueError(f"unknown update mode {update!r} "
                         "(optax|fused_bucket)")
    if quant and gather != "bucketed":
        raise ValueError(
            "quant=True quantizes the BUCKETED gather wire format; "
            f"gather={gather!r} has no bucket boundary to quantize at")
    if loss_of is None:
        loss_of = lambda logits, batch: cross_entropy_loss(logits, batch["y"])

    def build(param_specs):
        def step(state: TrainState, batch: Dict[str, jax.Array]):
            def loss_fn(params, mb):
                extra = apply_kwargs_of(mb) if apply_kwargs_of else {}
                # No logical_axis_rules scope: inside the manually-sharded
                # region GSPMD constraints don't apply (with no rules
                # active, flax's with_logical_constraint is a no-op).
                logits, sown = state.apply_fn(
                    {"params": params}, mb["x"], mutable="losses", **extra)
                aux = sum((leaf.sum() for leaf in
                           jax.tree.leaves(sown.get("losses", {}))),
                          start=jnp.float32(0.0))
                return loss_of(logits, mb) + aux, aux

            qamax = state.quant_state["amax"] if quant else None
            if update == "fused_bucket":
                # Bucket-major end to end: the optimizer update runs in
                # the accum region on the scan's reduce accumulators —
                # one fused kernel per bucket, grad norm included.
                count_inc = state.opt_state["count"] + 1
                scal = state.tx.scalars(count_inc)
                outs = overlap.microbatch_grads(
                    loss_fn, state.params, batch, mesh,
                    microbatches=microbatches,
                    bucket_bytes=state.tx.bucket_bytes,
                    reduce_op=reduce_op, has_aux=True,
                    param_specs=param_specs, hierarchy=hierarchy,
                    gather=gather, prefetch=prefetch,
                    fused=state.tx,
                    opt_slots=state.opt_state["slots"],
                    opt_scal=scal, quant_amax=qamax)
                loss, aux, new_params, new_slots, gnorm = outs[:5]
                new_state = state.replace(
                    step=state.step + 1, params=new_params,
                    opt_state={"count": count_inc, "slots": new_slots})
                if quant:
                    new_state = new_state.replace(
                        quant_state={"amax": outs[5]})
                return new_state, {"loss": loss, "grad_norm": gnorm,
                                   "aux_loss": aux}

            outs = overlap.microbatch_grads(
                loss_fn, state.params, batch, mesh,
                microbatches=microbatches, bucket_bytes=bucket_bytes,
                reduce_op=reduce_op, has_aux=True,
                param_specs=param_specs, hierarchy=hierarchy,
                gather=gather, prefetch=prefetch, quant_amax=qamax)
            loss, aux, grads = outs[:3]
            # ZeRO-3: grads carry the fsdp shard layout here, so the
            # optimizer update and the norm reduction below run shard-
            # local with GSPMD inserting only the tiny norm psum.
            new_state = state.apply_gradients(grads=grads)
            if quant:
                new_state = new_state.replace(
                    quant_state={"amax": outs[3]})
            gnorm = optax.global_norm(grads)
            return new_state, {"loss": loss, "grad_norm": gnorm,
                               "aux_loss": aux}

        return jax.jit(step, donate_argnums=(0,) if donate else ())

    # Layout detection memoized on the params' (treedef, shardings): one
    # flatten + hash per step on the hit path — fsdp_param_specs' spec
    # normalization and the jit-key build run only when the layout
    # actually changes (in practice, once).
    jitted: Dict[Any, Any] = {}

    def _jitted_for(state):
        leaves, treedef = jax.tree.flatten(state.params)
        key = (treedef,
               tuple(getattr(l, "sharding", None) for l in leaves))
        if key not in jitted:
            jitted[key] = build(overlap.fsdp_param_specs(
                state.params, mesh))
        return jitted[key]

    # Cold-start plane (tony_tpu.ckpt.aot): the persisted-executable
    # memo parallel to `jitted` — the raw jit stays what `inspect`
    # hands the analysis plane, the compiled executable is what the
    # hot loop calls. Keyed by (layout key, batch aval key); the CACHE
    # key is the digest of the LOWERED module: the training step closes
    # over an arbitrary user loss_of, which no config fingerprint can
    # soundly capture — so this path traces always (cheap, and what a
    # gang restart pays anyway) and skips only XLA compilation (the
    # dominant cost). A changed loss body, flag, or topology changes
    # the lowered text and misses cleanly.
    compiled: Dict[Any, Any] = {}

    def _compiled_for(state, batch):
        import hashlib

        from tony_tpu.ckpt import aot

        fn = _jitted_for(state)
        pleaves, ptreedef = jax.tree.flatten(state.params)
        bleaves, btreedef = jax.tree.flatten(batch)
        # The memo must key on EVERY state leaf's sharding, not just
        # the params': step 1's output re-shards the optimizer state
        # (replicated init -> the step's out_shardings), and a stale
        # Compiled hard-fails on the mismatch where raw jit would
        # silently re-trace. The wider key re-lowers, the lowered-HLO
        # digest shifts, and the cache misses cleanly into a compile.
        key = ((ptreedef,
                tuple(getattr(l, "sharding", None)
                      for l in jax.tree.leaves(state))),
               (btreedef,
                tuple((tuple(l.shape), str(l.dtype),
                       str(getattr(l, "sharding", None)))
                      for l in bleaves)))
        if key in compiled:
            return compiled[key]
        low = fn.lower(state, batch)
        fp = aot.make_fingerprint(
            "train_step", mesh=mesh,
            geometry={"microbatches": int(microbatches),
                      "bucket_bytes": int(bucket_bytes),
                      "reduce_op": reduce_op, "hierarchy": hierarchy,
                      "gather": gather, "prefetch": int(prefetch),
                      "update": update, "quant": bool(quant),
                      "donate": bool(donate)},
            tree=state, batch=batch,
            extra={"hlo": hashlib.sha256(
                low.as_text().encode()).hexdigest()})
        # The state treedef's static aux (the optax tx) doesn't pickle,
        # so the entry stores no call trees; both sides of the call are
        # re-derived here, from THIS process's args and lowering.
        ex = aot_cache.get(
            fp,
            in_tree=jax.tree_util.tree_structure(((state, batch), {})),
            out_tree=jax.tree_util.tree_structure(low.out_info))
        if ex is None:
            ex = low.compile()
            aot_cache.put(fp, ex)
        compiled[key] = ex
        return ex

    def stepper(state, batch):
        if update == "fused_bucket":
            from tony_tpu.ops import fused_optim

            if not isinstance(state.tx, fused_optim.FusedOptimizer):
                raise ValueError(
                    "update='fused_bucket' needs a state whose tx is a "
                    "tony_tpu.ops.fused_optim.FusedOptimizer (build it "
                    f"with create_train_state), got {type(state.tx)}")
            if bucket_bytes != overlap.DEFAULT_BUCKET_BYTES \
                    and bucket_bytes != state.tx.bucket_bytes:
                raise ValueError(
                    f"update='fused_bucket': bucket_bytes={bucket_bytes} "
                    f"disagrees with the FusedOptimizer's "
                    f"{state.tx.bucket_bytes} — the tx's value sized the "
                    f"bucket-resident opt state and wins; set it there")
        if quant:
            from tony_tpu.ops import quant as quant_mod

            if not quant_mod.is_quant_state(state):
                raise ValueError(
                    "quant=True needs a QuantTrainState carrying the "
                    "delayed-scaling amax state — attach it with "
                    "tony_tpu.ops.quant.with_gather_quant(state, mesh)")
            bb = state.tx.bucket_bytes if update == "fused_bucket" \
                else bucket_bytes
            if state.qconfig.bucket_bytes != bb:
                raise ValueError(
                    f"quant=True: the state's QuantConfig.bucket_bytes="
                    f"{state.qconfig.bucket_bytes} disagrees with the "
                    f"step's {bb} — the amax histories were sized for a "
                    f"different bucket plan; rebuild with "
                    f"with_gather_quant(bucket_bytes={bb})")
        with mesh_context(mesh):
            if aot_cache is not None:
                return _compiled_for(state, batch)(state, batch)
            return _jitted_for(state)(state, batch)

    def inspect(state):
        """Static-analysis hook: the jitted step this stepper would run
        for ``state``'s layout, plus the planner artifacts and config
        knobs it was built from — everything
        :func:`tony_tpu.analysis.analyze_accum_step` needs to audit the
        traced program against the plan it claims to execute. Plans come
        from :func:`~tony_tpu.parallel.overlap.step_plans`, the SAME
        derivation ``microbatch_grads`` uses, so the audit target can
        never drift from the step."""
        param_specs = overlap.fsdp_param_specs(state.params, mesh)
        bb = state.tx.bucket_bytes if update == "fused_bucket" \
            else bucket_bytes
        plan, gplan = overlap.step_plans(
            state.params, mesh, bucket_bytes=bb, param_specs=param_specs,
            prefetch=prefetch)
        return {"jitted": _jitted_for(state), "plan": plan,
                "gplan": gplan, "mesh": mesh, "update": update,
                "gather": gather, "reduce_op": reduce_op,
                "hierarchy": hierarchy, "donate": donate,
                "microbatches": microbatches, "bucket_bytes": bb,
                "param_specs": param_specs, "quant": quant,
                "fused": state.tx if update == "fused_bucket" else None}

    stepper.inspect = inspect
    return stepper


def shared_aot_cache(path: Optional[str] = None):
    """The gang-shared train AOT cache for ``make_accum_train_step
    (aot_cache=...)``, or ``None`` when the plane is unarmed — ``path``
    defaults from the ``TONY_TRAIN_AOT_CACHE`` env ``JAXRuntime``
    exports (``tony.train.aot-cache``), so a tony-submitted script arms
    it with one kwarg and runs unchanged everywhere else. Every worker
    opens the SAME durable directory: the first to lower a (mesh,
    geometry, lowered-HLO) fingerprint compiles and populates (put is
    stage-then-rename, first writer wins — concurrent gang mates race
    safely), the rest deserialize in milliseconds, and an elastic
    resize's re-gang stops paying a full recompile per topology change
    (the fingerprint keys the mesh, so each topology caches its own
    entry once)."""
    path = path or os.environ.get(constants.ENV_TRAIN_AOT_CACHE) or None
    if not path:
        return None
    from tony_tpu.ckpt.aot import AOTCache

    return AOTCache(path)


def train_loop(state: TrainState, step_fn: Callable[[TrainState, Any],
                                                    Tuple[TrainState, Any]],
               batches: Optional[Iterable[Any]] = None, *,
               data: Optional[Any] = None,
               ckpt_dir: Optional[str] = None,
               save_every: Optional[int] = None,
               keep: Optional[int] = None,
               restore_on_start: bool = True,
               mesh: Optional[Mesh] = None,
               save_final: bool = True,
               on_step: Optional[Callable[[int, Dict[str, Any]],
                                          None]] = None,
               drain_file: Optional[str] = None,
               publish_every: Optional[int] = None):
    """Drive ``step_fn`` over ``batches`` with integrated elastic
    checkpointing — the control-plane hook the gang-restart contract needs
    (``tony.am.retry-count``): attempt N+1 calls this exactly like attempt
    N did and resumes from the newest committed step automatically.

    ``ckpt_dir``/``save_every``/``keep`` default from the ``TONY_CKPT_*``
    env the JAXRuntime injects (``tony.ckpt.dir/every/keep``), so a
    tony-submitted job gets durable resume without touching its script;
    with no directory configured this is a plain fold over the batches.

    * ``restore_on_start``: restore the newest committed checkpoint into
      ``state`` before the first step (elastic: ``mesh`` maps the saved
      PartitionSpecs onto THIS attempt's topology when the state carries
      no committed shardings of its own); a no-op on the first attempt.
    * ``save_every=k``: async save (:class:`tony_tpu.ckpt
      .AsyncCheckpointer`) after every k-th step — the loop stalls only
      for the device→host snapshot, the commit overlaps later steps.
    * the executor reads the same directory and reports the last COMMITTED
      step to the AM over the heartbeat RPC, so the attempt log shows what
      a restart will resume from.

    ``data=`` attaches a framework-owned input iterator
    (:class:`tony_tpu.data.DeviceIterator` / ``PipelineIterator`` — any
    iterable with ``state()``/``restore()``) instead of ``batches``: the
    pipeline cursor is then saved INSIDE the same committed step as the
    train state (one atomic commit for both — see
    :mod:`tony_tpu.data.ckptio`) and restored with it, so a resumed run's
    example stream is element-identical to an uninterrupted one, even
    when the gang restarts with a different host count (the cursor is
    global; the new ShardSpecs re-slice it). A bare pre-data checkpoint
    restores the model alone and the stream starts from the iterator's
    current position.

    ``drain_file`` (default: the ``TONY_DRAIN_FILE`` env the executor
    injects) is the elastic-resize drain flag: the loop polls for it
    between steps, and when it appears commits model + data cursor
    SYNCHRONOUSLY (the resize controller may only re-gang against a
    durable manifest) and exits with ``SystemExit(EXIT_DRAINED)`` — the
    executor reports that code and the AM records the worker DRAINED,
    not failed.

    ``publish_every=n`` (default: the ``TONY_PUBLISH_EVERY`` env from
    ``tony.publish.every``) is the continuous-publication knob
    (:mod:`tony_tpu.publish`): after every n-th periodic save — and the
    final save — process 0 waits out the async COMMIT (the pointer may
    only ever name a manifest a restore can land) and advances the ckpt
    root's versioned ``published.json`` pointer through stage-and-
    rename. The executor announces the pointer on its heartbeat and the
    AM's follow mode rolls the serving fleet onto it, so a training
    gang continuously feeds the replicas it shares a control plane
    with — no manual checkpoint copying.

    Returns ``(state, last_metrics)``.
    """
    from tony_tpu import ckpt as ckpt_mod

    if (batches is None) == (data is None):
        raise ValueError("train_loop needs exactly one of batches= or "
                         "data=")
    if data is not None:
        batches = data
    stateful_data = (data is not None and hasattr(data, "state")
                     and hasattr(data, "restore"))
    if ckpt_dir is None:
        ckpt_dir = os.environ.get(constants.ENV_CKPT_DIR) or None
    if save_every is None:
        save_every = int(os.environ.get(constants.ENV_CKPT_EVERY, "0")
                         or 0)
    if keep is None:
        keep = int(os.environ.get(constants.ENV_CKPT_KEEP, "3") or 3)
    if drain_file is None:
        drain_file = os.environ.get(constants.ENV_DRAIN_FILE) or None
    if publish_every is None:
        publish_every = int(os.environ.get(constants.ENV_PUBLISH_EVERY,
                                           "0") or 0)
    mgr = None
    if ckpt_dir:
        from tony_tpu.data import ckptio

        mgr = ckpt_mod.AsyncCheckpointer(ckpt_dir, keep=keep)
        if restore_on_start:
            latest = ckpt_mod.latest_step(ckpt_dir)
            if latest is not None and ckptio.has_iter_state(ckpt_dir,
                                                           latest):
                # Wrapped {model, data_iter} checkpoint: unwrap keyed on
                # what the manifest CONTAINS, not on what this caller
                # passed — a batches= run restoring a data= run's save
                # must still get the model (the strict-mode tree-mismatch
                # KeyError it would otherwise hit reads like a wrong
                # model, not a wrapped checkpoint).
                # encode/decode_portable: planes with topology-bound live
                # state (the fused optimizer's bucket-resident moments)
                # restore through their portable leaf-major form and are
                # re-bound to THIS attempt's topology; identity for
                # everything else.
                state = ckpt_mod.decode_portable(ckpt_mod.restore_pytree(
                    ckpt_dir,
                    {ckptio.MODEL_KEY: ckpt_mod.encode_portable(state)},
                    step=latest, mesh=mesh)[ckptio.MODEL_KEY], mesh)
                if stateful_data:
                    data.restore(ckptio.load_iter_state(ckpt_dir, latest))
                else:
                    _log.warning(
                        "checkpoint step %d carries data-iterator state "
                        "but this train_loop has no stateful data=; the "
                        "model resumes, the input stream starts from the "
                        "beginning", latest)
            else:
                state = ckpt_mod.decode_portable(
                    ckpt_mod.restore_latest(
                        ckpt_dir, ckpt_mod.encode_portable(state),
                        mesh=mesh), mesh)

    def payload():
        # Saves go through the same portable codec: manifests carry the
        # topology-independent form (fused opt state leaf-major), so any
        # future attempt's topology can restore them.
        st = ckpt_mod.encode_portable(state)
        if stateful_data:
            return ckptio.wrap_for_save(st, data.state())
        return st

    metrics: Dict[str, Any] = {}
    done = 0
    saved_at: Optional[int] = None
    saves = 0
    published_step: Optional[int] = None

    def maybe_publish(step: int) -> None:
        # Continuous publication: the pointer may only advance over a
        # COMMITTED manifest, so the async save queue drains first
        # (wait() also re-raises any pending writer failure — a broken
        # commit must never be published). One writer per gang: only
        # process 0 advances the pointer, after every process's shards
        # are inside the commit by the wait barrier.
        nonlocal published_step
        if not publish_every or mgr is None or step == published_step:
            return
        from tony_tpu import publish as publish_mod

        mgr.wait()
        if jax.process_index() == 0:
            publish_mod.publish_step(ckpt_dir, step)
        published_step = step

    try:
        for batch in batches:
            state, metrics = step_fn(state, batch)
            done += 1
            chaos.kill_point(done)
            if on_step is not None:
                on_step(done, metrics)
            if mgr is not None and save_every and done % save_every == 0:
                saved_at = int(jax.device_get(state.step)) \
                    if hasattr(state, "step") else done
                mgr.save(payload(), step=saved_at)
                saves += 1
                if publish_every and saves % publish_every == 0:
                    maybe_publish(saved_at)
            if drain_file is not None and os.path.exists(drain_file):
                # Drain directive (elastic resize): commit model + cursor
                # SYNCHRONOUSLY — wait() both drains the async queue and
                # re-raises any pending writer failure, so EXIT_DRAINED
                # is only ever reported over a durable manifest.
                if mgr is not None:
                    here = int(jax.device_get(state.step)) \
                        if hasattr(state, "step") else done
                    if here != saved_at:
                        mgr.save(payload(), step=here)
                    mgr.wait()
                raise SystemExit(constants.EXIT_DRAINED)
        if mgr is not None and save_final and done:
            final = int(jax.device_get(state.step)) \
                if hasattr(state, "step") else done
            if final != saved_at:
                mgr.save(payload(), step=final)
            maybe_publish(final)
        if mgr is not None:
            mgr.wait()
    finally:
        if mgr is not None:
            mgr.close()
        # The loop owns the iteration: release the prefetch thread and
        # its staged device batches even when step_fn raises (close() is
        # idempotent and state() still reads the delivered cursor after).
        if data is not None and hasattr(data, "close"):
            data.close()
    return state, metrics


def train_stats_writer(path: Optional[str] = None, *,
                       flops_per_step: float = 0.0,
                       peak_flops: float = 0.0
                       ) -> Callable[[int, Dict[str, Any]], None]:
    """An ``on_step`` callback for :func:`train_loop` that publishes
    per-step cost telemetry — wall time, collective bytes (summed from
    :func:`tony_tpu.profiler.collective_report`'s planned per-issue
    payloads), and an MFU estimate (``flops_per_step / (step_time *
    peak_flops)`` when both are given) — to the executor's stats file
    through the atomic stage-and-rename idiom (tmp + ``os.replace``,
    the serve engine's ``write_stats`` contract). The executor's
    heartbeat loop piggybacks the file to the AM unchanged, where the
    history plane logs each window as a TRAIN_STEP event: one writer,
    one schema, no second bookkeeping path.

    ``path`` defaults to the ``TONY_SERVE_STATS`` env the executor
    injects into every task; outside a tony-run task (no env, no
    explicit path) the callback is a no-op so scripts run unchanged."""
    import json as json_mod
    import time as time_mod

    target = path or os.environ.get(constants.ENV_SERVE_STATS)
    last = {"t": time_mod.monotonic()}

    def on_step(step: int, metrics: Dict[str, Any]) -> None:
        now = time_mod.monotonic()
        dt = now - last["t"]
        last["t"] = now
        if not target:
            return
        nbytes = 0.0
        try:
            from tony_tpu import profiler
            for rec in profiler.collective_report().values():
                nbytes += float(sum(rec.get("nbytes") or ()))
        except Exception:
            pass                       # telemetry is advisory
        mfu = (flops_per_step / (dt * peak_flops)
               if flops_per_step > 0 and peak_flops > 0 and dt > 0
               else 0.0)
        payload = {"step": float(step), "step_time_s": float(dt),
                   "collective_bytes": nbytes, "mfu": float(mfu)}
        loss = metrics.get("loss") if isinstance(metrics, dict) else None
        if loss is not None:
            try:
                payload["loss"] = float(jax.device_get(loss))
            except (TypeError, ValueError):
                pass
        tmp = f"{target}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as fh:
                json_mod.dump(payload, fh)
            os.replace(tmp, target)
        except OSError:
            pass                       # advisory: never fail the step

    return on_step


def _validate_local_batch(mesh: Mesh, local_batch: Dict[str, Any],
                          seq_axis: bool = False) -> None:
    """Pre-flight the ``make_array_from_process_local_data`` contract and
    raise a ``ValueError`` NAMING the offending leaf — the raw failure is
    an opaque shape-assembly error deep inside jax. Checks (local-side
    proxies for "every process contributes the same local batch shape"):

    * every leaf is array-like with a batch dim, and all leaves agree on
      it (a per-process collective compare is impossible pre-assembly, but
      since every process runs this same check on the same contract, a
      divergent process fails by itself, by name);
    * the assembled global batch dim divides the mesh's batch sharding,
      and the local dim divides this process's share of it;
    * with ``seq_axis``, the (process-replicated) sequence dim divides the
      ring axis.
    """
    flat = jax.tree_util.tree_flatten_with_path(local_batch)[0]
    if not flat:
        return
    nproc = jax.process_count()
    spec0 = par.batch_sharding(mesh).spec[0]
    names = spec0 if isinstance(spec0, tuple) else (spec0,)
    n_shards = 1
    for a in names:
        n_shards *= mesh.shape[a]
    ref_path = ref_dim = None
    for path, leaf in flat:
        name = jax.tree_util.keystr(path)
        if not hasattr(leaf, "shape") or np.ndim(leaf) == 0:
            raise ValueError(
                f"global_batch leaf {name}: expected an array with a "
                f"leading batch dim, got {type(leaf).__name__} of rank "
                f"{np.ndim(leaf)}")
        dim = int(np.shape(leaf)[0])
        if ref_dim is None:
            ref_path, ref_dim = name, dim
        elif dim != ref_dim:
            raise ValueError(
                f"global_batch leaf {name}: local batch dim {dim} != "
                f"{ref_dim} (leaf {ref_path}) — every leaf of every "
                f"process must contribute the same local batch count")
        if seq_axis and np.ndim(leaf) >= 2:
            seq = int(np.shape(leaf)[1])
            seq_shards = mesh.shape[par.SEQ]
            if seq % seq_shards:
                raise ValueError(
                    f"global_batch leaf {name}: sequence dim {seq} not "
                    f"divisible by the {seq_shards}-way ring axis "
                    f"({par.SEQ!r}) of the mesh")
    global_dim = ref_dim * nproc
    if global_dim % n_shards:
        raise ValueError(
            f"global_batch leaf {ref_path}: local batch dim {ref_dim} x "
            f"{nproc} process(es) = global {global_dim}, not divisible by "
            f"the {n_shards}-way batch sharding {tuple(names)} of the "
            f"mesh — pad or resize the per-process batch")
    if n_shards % nproc == 0:
        per_proc = n_shards // nproc
        if per_proc and ref_dim % per_proc:
            raise ValueError(
                f"global_batch leaf {ref_path}: local batch dim {ref_dim} "
                f"not divisible by this process's {per_proc} addressable "
                f"batch shard(s) ({n_shards}-way sharding over {nproc} "
                f"process(es))")


# Contracts already validated, mesh → {(seq_axis, treedef, leaf shapes)}:
# the shape contract is invariant per pipeline, so per-step callers pay
# the full pre-flight once, not every step. Only successes are cached —
# a bad contract re-raises on every call. Weakly keyed so cached meshes
# are released with their last outside reference; per-mesh bound as a
# backstop against pathological ever-changing shapes (when full,
# validation just runs).
_VALIDATED_CONTRACTS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_VALIDATED_CONTRACTS_MAX = 256


def global_batch(mesh: Mesh, local_batch: Dict[str, Any],
                 seq_axis: bool = False,
                 check: bool = True) -> Dict[str, jax.Array]:
    """Assemble the logically-global batch from this process's local shard —
    every process calls this with its own slice (multi-host feeding).
    ``check`` pre-flights the shape contract with a leaf-naming
    ``ValueError`` instead of jax's opaque assembly failure (memoized per
    (mesh, treedef, leaf-shape) contract, so the per-step cost is one
    flatten + set lookup)."""
    if check:
        leaves, treedef = jax.tree_util.tree_flatten(local_batch)
        key = (seq_axis, treedef, tuple(np.shape(l) for l in leaves))
        seen = _VALIDATED_CONTRACTS.setdefault(mesh, set())
        if key not in seen:
            _validate_local_batch(mesh, local_batch, seq_axis=seq_axis)
            if len(seen) < _VALIDATED_CONTRACTS_MAX:
                seen.add(key)

    def put(x):
        # Rank-1 leaves (labels, weights) can't carry the seq dim.
        sharding = par.batch_sharding(
            mesh, seq_axis=seq_axis and x.ndim >= 2)
        return jax.make_array_from_process_local_data(sharding, x)
    return jax.tree.map(put, local_batch)
