"""Checkpoint/resume helper — thin compat shim over :mod:`tony_tpu.ckpt`.

The reference delegates checkpointing entirely to user code (HDFS dirs that
survive AM restarts; TonY just restarts the gang and the script restores).
This class keeps the seed-era surface (``save`` / ``restore_or`` /
``latest_step`` / ``close``) so existing user scripts resume across gang
restarts (``tony.am.retry-count``) unchanged — but it now rides the native
async subsystem instead of orbax (no longer required): crash-consistent
manifest commits, sharded per-process writes, elastic restore.

Fixed here vs the orbax shim: ``restore_or`` used to build its abstract
target with ``sharding=getattr(x, "sharding", None)`` — a leaf WITHOUT a
committed sharding (host numpy arrays, freshly-created states) silently
restored replicated even when the checkpoint recorded a mesh layout. The
native restore resolves each leaf's layout from the target's committed
sharding when present and from the manifest's PartitionSpec otherwise, so
shardings survive either way.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Optional

from tony_tpu import ckpt as _ckpt


class Checkpointer:
    """Directory-bound save/restore manager (seed-compatible surface)."""

    def __init__(self, directory: str | Path, max_to_keep: int = 3):
        self.directory = Path(directory).resolve()
        self.directory.mkdir(parents=True, exist_ok=True)
        self._mgr = _ckpt.AsyncCheckpointer(self.directory, keep=max_to_keep)

    def save(self, state: Any, step: Optional[int] = None,
             wait: bool = True) -> None:
        """Save a pytree (e.g. a TrainState); all processes must call.
        ``wait=False`` returns after the device→host snapshot and commits
        in the background (:class:`tony_tpu.ckpt.AsyncCheckpointer`)."""
        self._mgr.save(state, step=step, block=wait)

    def latest_step(self) -> Optional[int]:
        return _ckpt.latest_step(self.directory)

    def restore_or(self, state: Any, mesh: Any = None) -> Any:
        """Restore the latest checkpoint shaped/sharded like ``state``, or
        return ``state`` unchanged when none exists (first attempt).
        ``mesh`` enables elastic restore onto a topology other than the
        one the state's own shardings (if any) describe."""
        # Drain in-flight async saves first: "latest" must mean latest.
        self._mgr.wait()
        return _ckpt.restore_latest(self.directory, state, mesh=mesh)

    def wait_until_finished(self) -> None:
        self._mgr.wait()

    def close(self) -> None:
        self._mgr.close()
