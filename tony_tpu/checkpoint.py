"""Checkpoint/resume helper: the idiomatic orbax wrapper (SURVEY.md §5.4).

The reference delegates checkpointing entirely to user code (HDFS dirs that
survive AM restarts; TonY just restarts the gang and the script restores).
The TPU rebuild keeps that contract — the AM checkpoints nothing — but ships
this helper so JAXRuntime jobs resume by default across gang restarts
(``tony.am.retry-count``): sharded arrays save/restore with their mesh
layouts intact, every process participates (orbax coordinates the writes),
and ``restore_or`` is a no-op on the first attempt.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Optional

import jax


class Checkpointer:
    """Thin orbax CheckpointManager wrapper bound to one directory."""

    def __init__(self, directory: str | Path, max_to_keep: int = 3):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.directory = Path(directory).resolve()
        self.mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True))

    def save(self, state: Any, step: Optional[int] = None,
             wait: bool = True) -> None:
        """Save a pytree (e.g. a TrainState); all processes must call."""
        if step is None:
            step = int(jax.device_get(state.step)) if hasattr(state, "step") \
                else 0
        self.mgr.save(step, args=self._ocp.args.StandardSave(state))
        if wait:
            self.mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self.mgr.latest_step()

    def restore_or(self, state: Any) -> Any:
        """Restore the latest checkpoint shaped/sharded like ``state``, or
        return ``state`` unchanged when none exists (first attempt)."""
        latest = self.mgr.latest_step()
        if latest is None:
            return state
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape") else x,
            state)
        return self.mgr.restore(
            latest, args=self._ocp.args.StandardRestore(abstract))

    def close(self) -> None:
        self.mgr.close()
