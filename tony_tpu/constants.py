"""Shared constants: env-var names, file names, well-known job types.

Mirrors the role of ``com.linkedin.tony.Constants`` (tony-core, upstream path
``tony-core/src/main/java/com/linkedin/tony/Constants.java``, unverified — see
SURVEY.md §0): the single place where the env-var contract between the AM, the
task executors, and user code is written down.
"""

# --- Environment contract: AM -> TaskExecutor -------------------------------
# (reference: Constants.JOB_NAME / TASK_INDEX / AM_HOST / AM_PORT etc., set in
#  TonyApplicationMaster#buildContainerLaunchContext)
ENV_JOB_NAME = "TONY_JOB_NAME"              # jobtype, e.g. "worker", "ps", "chief"
ENV_TASK_INDEX = "TONY_TASK_INDEX"          # integer index within the jobtype
ENV_TASK_NUM = "TONY_NUM_TASKS"             # total number of tasks in the job
ENV_AM_ADDRESS = "TONY_AM_ADDRESS"          # host:port of the AM ApplicationRpc
ENV_APP_ID = "TONY_APP_ID"                  # application id, e.g. "app_1700000000_0001"
ENV_ATTEMPT_ID = "TONY_ATTEMPT_ID"          # AM attempt ordinal (gang restart)
ENV_CONF_PATH = "TONY_CONF_PATH"            # path to the serialized job config
ENV_CONTAINER_ID = "TONY_CONTAINER_ID"      # container id for this executor
ENV_LOG_DIR = "TONY_LOG_DIR"                # directory for executor+user logs
ENV_SRC_DIR = "TONY_SRC_DIR"                # localized user source directory
ENV_VENV = "TONY_VENV"                      # localized virtualenv (optional)
ENV_RESOURCES_DIR = "TONY_RESOURCES_DIR"    # staged tony.containers.resources
ENV_SUBMIT_TS = "TONY_SUBMIT_TS"            # client submit wall-clock (epoch s)

# --- Environment contract: TaskExecutor -> user process ---------------------
# (reference: MLGenericRuntime common env + per-runtime additions)
ENV_JOB_TYPE = "JOB_NAME"                   # TonY exports JOB_NAME/TASK_INDEX too
ENV_TASK_INDEX_USER = "TASK_INDEX"
ENV_DIST_SPEC = "CLUSTER_SPEC"              # JSON {jobtype: ["host:port", ...]}
ENV_TB_PORT = "TB_PORT"                     # reserved TensorBoard port (chief/tb)

# JAXRuntime rendezvous (the north-star JAX path; consumed by
# tony_tpu.distributed.initialize() and by jax.distributed directly)
ENV_COORDINATOR_ADDRESS = "TONY_COORDINATOR_ADDRESS"
ENV_PROCESS_ID = "TONY_PROCESS_ID"
ENV_NUM_PROCESSES = "TONY_NUM_PROCESSES"
ENV_LOCAL_DEVICE_IDS = "TONY_LOCAL_DEVICE_IDS"
ENV_PROFILER_PORT = "TONY_PROFILER_PORT"    # jax.profiler server (§5.1 hook)
# Checkpoint plane (tony_tpu.ckpt): JAXRuntime exports these from
# tony.ckpt.dir/every/keep; train.train_loop reads them as its defaults,
# and the executor scans the same dir to report the last COMMITTED step
# over the heartbeat RPC.
ENV_CKPT_DIR = "TONY_CKPT_DIR"
ENV_CKPT_EVERY = "TONY_CKPT_EVERY"
ENV_CKPT_KEEP = "TONY_CKPT_KEEP"
# Input-data plane (tony_tpu.data): JAXRuntime exports tony.data.seed so
# the whole gang derives the SAME deterministic example stream without the
# script threading a seed through (Dataset's default seed). The shard
# itself needs no new env — ShardSpec.from_env reads the rendezvous pair
# (TONY_PROCESS_ID/TONY_NUM_PROCESSES) with the generic executor pair
# (TONY_TASK_INDEX/TONY_NUM_TASKS) as fallback.
ENV_DATA_SEED = "TONY_DATA_SEED"
# Serving plane (tony_tpu.serve): the executor exports a per-container
# stats-file path; the replica's engine publishes qps/p99/queue-depth
# there and the executor's heartbeat loop piggybacks it to the AM (both
# sides jax-free), where the replica autoscaler reads it.
ENV_SERVE_STATS = "TONY_SERVE_STATS"
# Elastic resize (tony_tpu.am.resize): the executor exports a drain-file
# path; when the AM's heartbeat response carries the drain directive the
# executor creates the file, and train_loop — polling it between steps —
# commits model+data-cursor and exits EXIT_DRAINED.
ENV_DRAIN_FILE = "TONY_DRAIN_FILE"
# Continuous weight publication (tony_tpu.publish): JAXRuntime exports
# tony.publish.every; train_loop advances the ckpt root's published.json
# pointer every N committed periodic saves, and the executor's heartbeat
# loop reads the pointer (jax-free) and announces it to the AM.
ENV_PUBLISH_EVERY = "TONY_PUBLISH_EVERY"
# Shared per-gang train AOT cache dir (tony_tpu.ckpt.aot): exported from
# tony.train.aot-cache; make_accum_train_step deserializes a gang mate's
# compiled step instead of re-tracing (first writer wins on populate).
ENV_TRAIN_AOT_CACHE = "TONY_TRAIN_AOT_CACHE"

# TFRuntime / PyTorchRuntime / HorovodRuntime / MXNetRuntime rendezvous vars
ENV_TF_CONFIG = "TF_CONFIG"
ENV_MASTER_ADDR = "MASTER_ADDR"
ENV_MASTER_PORT = "MASTER_PORT"
ENV_RANK = "RANK"
ENV_WORLD_SIZE = "WORLD_SIZE"
ENV_LOCAL_RANK = "LOCAL_RANK"
ENV_INIT_METHOD = "INIT_METHOD"
ENV_HOROVOD_CONTROLLER = "HOROVOD_CONTROLLER"
ENV_HOROVOD_RENDEZVOUS_ADDR = "HOROVOD_GLOO_RENDEZVOUS_ADDR"
ENV_HOROVOD_RENDEZVOUS_PORT = "HOROVOD_GLOO_RENDEZVOUS_PORT"
ENV_HOROVOD_RANK = "HOROVOD_RANK"
ENV_HOROVOD_SIZE = "HOROVOD_SIZE"
ENV_HOROVOD_LOCAL_RANK = "HOROVOD_LOCAL_RANK"
ENV_HOROVOD_LOCAL_SIZE = "HOROVOD_LOCAL_SIZE"
ENV_HOROVOD_CROSS_RANK = "HOROVOD_CROSS_RANK"
ENV_HOROVOD_CROSS_SIZE = "HOROVOD_CROSS_SIZE"
ENV_DMLC_PS_ROOT_URI = "DMLC_PS_ROOT_URI"
ENV_DMLC_PS_ROOT_PORT = "DMLC_PS_ROOT_PORT"
ENV_DMLC_ROLE = "DMLC_ROLE"
ENV_DMLC_NUM_SERVER = "DMLC_NUM_SERVER"
ENV_DMLC_NUM_WORKER = "DMLC_NUM_WORKER"

# TPU topology env injected by JAXRuntime on real pods (libtpu contract)
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
ENV_TPU_VISIBLE_DEVICES = "TPU_VISIBLE_DEVICES"
ENV_TPU_CHIPS_PER_HOST_BOUNDS = "TPU_CHIPS_PER_HOST_BOUNDS"
# Host-subdivision contract (several tasks sharing one host's chips):
ENV_TPU_PROCESS_BOUNDS = "TPU_PROCESS_BOUNDS"
ENV_TPU_CHIPS_PER_PROCESS_BOUNDS = "TPU_CHIPS_PER_PROCESS_BOUNDS"
ENV_TPU_PROCESS_ADDRESSES = "TPU_PROCESS_ADDRESSES"
ENV_TPU_PROCESS_PORT = "TPU_PROCESS_PORT"
ENV_CLOUD_TPU_TASK_ID = "CLOUD_TPU_TASK_ID"
# Multi-slice (megascale) DCN coordination: exported when tony.jax.slices>1
# so libtpu bridges the slices over DCN and the hierarchical gradient
# reduce (tony_tpu.parallel.overlap) has a cross-slice axis to ride.
ENV_MEGASCALE_COORDINATOR_ADDRESS = "MEGASCALE_COORDINATOR_ADDRESS"
ENV_MEGASCALE_NUM_SLICES = "MEGASCALE_NUM_SLICES"
ENV_MEGASCALE_SLICE_ID = "MEGASCALE_SLICE_ID"
ENV_MEGASCALE_PORT = "MEGASCALE_PORT"
# XLA compiler knobs (JAXRuntime injects the comm/compute-overlap set —
# latency-hiding scheduler + async collectives — unless disabled by conf)
ENV_XLA_FLAGS = "XLA_FLAGS"

# --- Well-known job types ---------------------------------------------------
# (reference: open-ended; these are the conventional names used by the success
#  policy in TonyApplicationMaster / TonySession)
CHIEF = "chief"
MASTER = "master"
PS = "ps"
WORKER = "worker"
EVALUATOR = "evaluator"
TENSORBOARD = "tensorboard"
NOTEBOOK = "notebook"
DRIVER = "driver"               # Horovod-style driver task
SCHEDULER = "scheduler"         # MXNet kvstore scheduler
SERVE = "serve"                 # online-serving replica (tony_tpu.serve)

# Job types whose completion drives the "chief done => job done" policy.
CHIEF_LIKE_JOB_TYPES = (CHIEF, MASTER)

# Sidecar job types: never part of the ML rendezvous world (excluded from
# RANK/WORLD_SIZE/coordinator selection the way the reference's TFRuntime
# excludes them from TF_CONFIG). Distinct from *untracked* types: ``ps`` is
# untracked by default but IS a cluster member.
SIDECAR_JOB_TYPES = (TENSORBOARD, NOTEBOOK, DRIVER)

# --- File-layout conventions ------------------------------------------------
TONY_XML = "tony.xml"                       # user config file name (compat)
TONY_JOB_JSON = "tony-job.json"             # serialized effective config
JHIST_SUFFIX = ".jhist"                     # history file (JSONL here, Avro in ref)
JHIST_INPROGRESS_SUFFIX = ".jhist.inprogress"
EVENTS_DIR_INTERMEDIATE = "intermediate"    # AM writes here while running
EVENTS_DIR_FINISHED = "finished"            # moved here on completion
EXECUTOR_LOG_NAME = "executor.log"
USER_STDOUT_NAME = "stdout.log"
USER_STDERR_NAME = "stderr.log"

# --- Exit codes (reference: TaskExecutor / TonyClient contract) -------------
EXIT_SUCCESS = 0
EXIT_FAILURE = 1
EXIT_AM_ERROR = 10          # AM internal error
EXIT_LOST_TASK = 11         # task lost to missed heartbeats
EXIT_PREEMPTED = 12         # container preempted by the scheduler
EXIT_KILLED = 13            # killed by client / untracked-task teardown
EXIT_DRAINED = 14           # clean drain exit (elastic resize commit)
