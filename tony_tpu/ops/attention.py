"""Flash attention: fused online-softmax attention as a pallas TPU kernel.

The score matrix never leaves VMEM: each (batch·head, q-block) grid cell
streams K/V blocks through the online-softmax recurrence (running max m,
normalizer l, accumulator acc — same math as
:mod:`tony_tpu.parallel.ring_attention`, which runs the recurrence *across
chips* while this kernel runs it *within* one), so HBM traffic is O(T·D)
instead of O(T²) and the matmuls hit the MXU in bf16/f32 with f32
accumulation. Causal runs skip entire k-blocks above the diagonal — the
dominant win for long sequences.

Public entry :func:`flash_attention` dispatches: pallas kernel on TPU (or
``interpret=True`` for CPU tests), pure-JAX :func:`reference_attention`
elsewhere; the backward pass is the reference VJP under ``jax.checkpoint``
semantics (recompute, no saved T×T residuals).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True,
                        scale: Optional[float] = None) -> jax.Array:
    """Plain attention over [B, H, T, D], f32 softmax accumulation."""
    d = q.shape[-1]
    scale = d ** -0.5 if scale is None else scale
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t_q, t_k = q.shape[2], k.shape[2]
        mask = (jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
                >= jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _causal_mask(s, qi, bq, kb, block_k):
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                  causal: bool, scale: float):
    """One grid cell: q-block [Bq, D] against the full K/V [T, D] in VMEM,
    streamed in block_k chunks through the online-softmax recurrence. Also
    writes the log-sum-exp rows the backward kernels reconstruct p from."""
    bq, d = q_ref.shape
    t = k_ref.shape[0]
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    a0 = jnp.zeros((bq, d), jnp.float32)

    if causal:
        # Only k-blocks touching or below the diagonal contribute.
        num_kb = pl.cdiv((qi + 1) * bq, block_k)
    else:
        num_kb = pl.cdiv(t, block_k)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [Bq, Bk]
        if causal:
            s = _causal_mask(s, qi, bq, kb, block_k)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, a0))
    l_safe = jnp.where(l > 0, l, 1.0)
    o_ref[:] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[:] = (m + jnp.log(l_safe)).reshape(bq)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref,
                         *, block_k: int, causal: bool, scale: float):
    """dq for one q-block: recompute p from (q, k, lse) per k-block —
    ds = p·(dpᵀ−D); dq += ds·k·scale. No T×T buffer ever materializes."""
    bq, d = q_ref.shape
    t = k_ref.shape[0]
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    o = o_ref[:].astype(jnp.float32)
    lse = lse_ref[:].reshape(bq, 1)
    D = jnp.sum(do * o, axis=-1, keepdims=True)          # [Bq, 1]
    num_kb = pl.cdiv((qi + 1) * bq, block_k) if causal else pl.cdiv(
        t, block_k)

    def body(kb, dq):
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, bq, kb, block_k)
        p = jnp.exp(s - lse)                              # exact softmax
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - D)
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    dq = jax.lax.fori_loop(0, num_kb, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                          dk_ref, dv_ref, *, block_q: int, causal: bool,
                          scale: float):
    """dk/dv for one k-block: iterate q-blocks (from the diagonal down when
    causal): dv += pᵀ·do; dk += dsᵀ·q·scale."""
    bk, d = k_ref.shape
    t = q_ref.shape[0]
    kj = pl.program_id(1)
    k_blk = k_ref[:].astype(jnp.float32)
    v_blk = v_ref[:].astype(jnp.float32)
    num_qb = pl.cdiv(t, block_q)
    qb0 = (kj * bk) // block_q if causal else 0

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        o = o_ref[pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(qb * block_q, block_q)].reshape(block_q, 1)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qb, block_q, kj, bk)
        p = jnp.exp(s - lse)                              # [Bq, Bk]
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        D = jnp.sum(do * o, axis=-1, keepdims=True)
        ds = p * (dp - D)
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        return dk_new, dv_new

    zeros = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(qb0, num_qb, body, (zeros, zeros))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    b, h, t, d = q.shape
    tk = k.shape[2]
    grid = (b * h, pl.cdiv(t, block_q))
    qr = q.reshape(b * h, t, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)
    kernel = functools.partial(_flash_kernel, block_k=block_k,
                               causal=causal, scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((None, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((None, block_q), lambda bh, i: (bh, i)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, t), jnp.float32),
        ),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * t * tk * d // (2 if causal else 1),
            bytes_accessed=(qr.size + kr.size + vr.size) * q.dtype.itemsize,
            transcendentals=b * h * t * tk),
    )(qr, kr, vr)
    return out.reshape(b, h, t, d), lse.reshape(b, h, t)


def _flash_backward(q, k, v, do, o, lse, causal, scale, block_q, block_k,
                    interpret):
    b, h, t, d = q.shape
    tk = k.shape[2]
    bh = b * h
    qr, kr, vr = (x.reshape(bh, -1, d) for x in (q, k, v))
    dor, outr = do.reshape(bh, t, d), o.reshape(bh, t, d)
    lser = lse.reshape(bh, t)
    q_spec = pl.BlockSpec((None, block_q, d), lambda g, i: (g, i, 0))
    kv_full = pl.BlockSpec((None, tk, d), lambda g, i: (g, 0, 0))
    q_full = pl.BlockSpec((None, t, d), lambda g, i: (g, 0, 0))
    lse_blk = pl.BlockSpec((None, block_q), lambda g, i: (g, i))
    lse_full = pl.BlockSpec((None, t), lambda g, i: (g, 0))
    k_spec = pl.BlockSpec((None, block_k, d), lambda g, j: (g, j, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                          causal=causal, scale=scale),
        grid=(bh, pl.cdiv(t, block_q)),
        in_specs=[q_spec, kv_full, kv_full, q_spec, q_spec, lse_blk],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        interpret=interpret,
    )(qr, kr, vr, dor, outr, lser)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          causal=causal, scale=scale),
        grid=(bh, pl.cdiv(tk, block_k)),
        in_specs=[q_full, k_spec, k_spec, q_full, q_full, lse_full],
        out_specs=(k_spec, k_spec),
        out_shape=(jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, tk, d), v.dtype)),
        interpret=interpret,
    )(qr, kr, vr, dor, outr, lser)
    return (dq.reshape(q.shape), dk.reshape(k.shape), dv.reshape(v.shape))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    out, _ = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                            interpret)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                              interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, residuals, g):
    q, k, v, out, lse = residuals
    return _flash_backward(q, k, v, g, out, lse, causal, scale, block_q,
                           block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Fused attention over ``[batch, heads, seq, head_dim]``.

    Dispatch: the pallas kernel on TPU backends (or when ``interpret=True``
    forces the pallas interpreter — how CPU tests cover the kernel), the
    pure-JAX reference otherwise. Sequence length must divide by the block
    sizes on the kernel path; callers pad or fall back.
    """
    d = q.shape[-1]
    scale = d ** -0.5 if scale is None else scale
    t, tk = q.shape[2], k.shape[2]
    if interpret is None:
        on_tpu = jax.default_backend() == "tpu"
        if not on_tpu:
            return reference_attention(q, k, v, causal, scale)
        interpret = False
    block_q = min(block_q, t)
    block_k = min(block_k, tk)
    if t % block_q or tk % block_k:
        return reference_attention(q, k, v, causal, scale)
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)
