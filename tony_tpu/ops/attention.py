"""Flash attention: fused online-softmax attention as a pallas TPU kernel.

The score matrix never leaves VMEM: each (batch·head, q-block) grid cell
streams K/V blocks through the online-softmax recurrence (running max m,
normalizer l, accumulator acc — same math as
:mod:`tony_tpu.parallel.ring_attention`, which runs the recurrence *across
chips* while this kernel runs it *within* one), so HBM traffic is O(T·D)
instead of O(T²) and the matmuls hit the MXU in bf16/f32 with f32
accumulation. Causal runs skip entire k-blocks above the diagonal — the
dominant win for long sequences.

Public entry :func:`flash_attention` dispatches: pallas kernel on TPU (or
``interpret=True`` for CPU tests), pure-JAX :func:`reference_attention`
elsewhere; the backward pass is the reference VJP under ``jax.checkpoint``
semantics (recompute, no saved T×T residuals).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# The per-row log-sum-exp is carried as [rows, _LSE_LANES] with the value
# replicated across lanes: a (block_q,) 1-D block has its second-to-minor
# dim squeezed, which the Mosaic TPU lowering rejects — blocks need a
# (sublane, lane) shape whose dims divide the (8, 128) f32 tile or equal
# the array dims. Lane-replicating is the same layout the reference JAX
# TPU flash kernel uses for its l/m residuals.
_LSE_LANES = 8


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True,
                        scale: Optional[float] = None) -> jax.Array:
    """Plain attention over [B, H, T, D], f32 softmax accumulation.
    K/V may carry fewer heads (GQA); they are repeated up to H here —
    this is the semantic spec the zero-copy kernels are tested against."""
    d = q.shape[-1]
    scale = d ** -0.5 if scale is None else scale
    if k.shape[1] != q.shape[1]:
        reps = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, reps, axis=1)
        v = jnp.repeat(v, reps, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t_q, t_k = q.shape[2], k.shape[2]
        mask = (jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
                >= jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _causal_mask(s, qi, bq, kb, block_k):
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


def _mask_s(s, qi, bq, kb, block_k, causal, kv_len):
    """Score masking shared by every kernel body: the causal triangle
    and/or the key-length mask for end-padded K/V (``kv_len`` = the REAL
    key count, a static int — ``None`` means no padded keys to hide).
    Both are resolved at trace time, so the unmasked paths compile to
    exactly the pre-mask kernels. Padded keys never fully mask a k-block
    (padding rounds up to the block size, so the last block keeps >= 1
    real key) — the online-softmax max can't get stuck at -inf."""
    if causal:
        s = _causal_mask(s, qi, bq, kb, block_k)
    if kv_len is not None:
        k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                        s.shape, 1)
        s = jnp.where(k_pos < kv_len, s, _NEG_INF)
    return s


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                  acc_scr, *, causal: bool, scale: float, qi_axis: int = 1,
                  kv_len: Optional[int] = None):
    """Streamed-KV flash forward: grid ``(..., qi, kb)`` with the k-block
    axis INNERMOST, so K/V arrive one ``[Bk, D]`` block at a time (VMEM
    stays O(block), any context length fits) while the online-softmax
    state (running max m, normalizer l, accumulator acc) carries across
    k-steps in VMEM scratch. The q/o/lse blocks keep a constant index over
    the k axis, so they stay resident and o/lse flush once, written at the
    last k-step. Causal q-blocks skip the compute (not the schedule) of
    k-blocks above the diagonal via predication. Also writes the
    log-sum-exp rows the backward kernels reconstruct p from.
    ``qi_axis`` is which grid axis carries the q-block index (the k axis
    is ``qi_axis + 1``): 1 for the [B·H, T, D] layout's (bh, i, kb) grid,
    2 for the packed [B, T, H·D] layout's (b, h, i, kb) grid."""
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    qi = pl.program_id(qi_axis)
    kb = pl.program_id(qi_axis + 1)
    nkb = pl.num_programs(qi_axis + 1)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    contributes = (kb * bk < (qi + 1) * bq) if causal else (kb >= 0)

    @pl.when(contributes)
    def _step():
        # Matmul inputs stay in their storage dtype (bf16): bf16×bf16
        # products are exact in the MXU's f32 accumulator, so this loses
        # nothing over upcast-then-dot. Softmax math runs in f32; p casts
        # back for the PV matmul.
        q = q_ref[:]
        s = jax.lax.dot_general(
            q, k_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [Bq, Bk]
        s = _mask_s(s, qi, bq, kb, bk, causal, kv_len)
        m = m_scr[:, 0:1]
        l = l_scr[:, 0:1]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(kb == nkb - 1)
    def _finalize():
        m = m_scr[:, 0:1]
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[:] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[:] = jnp.broadcast_to(m + jnp.log(l_safe),
                                      (bq, _LSE_LANES))


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref,
                         dq_scr, *, causal: bool, scale: float,
                         qi_axis: int = 1, kv_len: Optional[int] = None):
    """dq, streamed like the forward (grid ``(..., qi, kb)``, k innermost,
    dq accumulated in VMEM scratch): recompute p from (q, k, lse) per
    k-block — ds = p·(dpᵀ−D); dq += ds·k·scale. No T×T buffer and no
    full-length K/V ever materialize."""
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    qi = pl.program_id(qi_axis)
    kb = pl.program_id(qi_axis + 1)
    nkb = pl.num_programs(qi_axis + 1)

    @pl.when(kb == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    contributes = (kb * bk < (qi + 1) * bq) if causal else (kb >= 0)

    @pl.when(contributes)
    def _step():
        q = q_ref[:]
        do = do_ref[:]
        D = jnp.sum(do.astype(jnp.float32) * o_ref[:].astype(jnp.float32),
                    axis=-1, keepdims=True)              # [Bq, 1]
        lse = lse_ref[:, 0:1]                            # [Bq, 1]
        s = jax.lax.dot_general(
            q, k_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _mask_s(s, qi, bq, kb, bk, causal, kv_len)
        p = jnp.exp(s - lse)                              # exact softmax
        dp = jax.lax.dot_general(
            do, v_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - D)).astype(k_ref.dtype)
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, k_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(kb == nkb - 1)
    def _finalize():
        dq_ref[:] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                          dk_ref, dv_ref, dk_scr, dv_scr, *, causal: bool,
                          scale: float, qi_axis: int = 1, nqb: int = 0,
                          kv_len: Optional[int] = None):
    """dk/dv, streamed: grid ``(..., kj, qx)`` with the q-side axis
    INNERMOST — q/do/o/lse arrive one block at a time while this k-block's
    dk/dv accumulate in VMEM scratch (dv += pᵀ·do; dk += dsᵀ·q·scale).
    Causal k-blocks skip q-blocks strictly above the diagonal.

    GQA: one kv head serves ``reps`` query heads, so the innermost axis is
    the FLATTENED (rep, q-block) index of size reps·nqb — the callers'
    q-side index maps decode it — and dk/dv accumulate across the whole
    sweep. ``nqb`` is the per-head q-block count (0 ⇒ no grouping: the
    axis is plain q-blocks)."""
    bk, d = k_ref.shape
    bq = q_ref.shape[0]
    kj = pl.program_id(qi_axis)
    qx = pl.program_id(qi_axis + 1)
    nqx = pl.num_programs(qi_axis + 1)
    qb = qx % nqb if nqb else qx

    @pl.when(qx == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    contributes = ((qb + 1) * bq > kj * bk) if causal else (qb >= 0)

    @pl.when(contributes)
    def _step():
        q = q_ref[:]
        do = do_ref[:]
        lse = lse_ref[:, 0:1]                             # [Bq, 1]
        s = jax.lax.dot_general(
            q, k_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _mask_s(s, qb, bq, kj, bk, causal, kv_len)
        p = jnp.exp(s - lse)                              # [Bq, Bk]
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        D = jnp.sum(do.astype(jnp.float32) * o_ref[:].astype(jnp.float32),
                    axis=-1, keepdims=True)
        ds = (p * (dp - D)).astype(q.dtype)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(qx == nqx - 1)
    def _finalize():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _dkv_resident_nogroup(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                          dk_ref, dv_ref, **kw):
    """reps==1 wrapper: no scratch operands, so the pallas_call allocates
    zero dead VMEM on exactly the variant whose dispatch is gated on VMEM
    fit (the kernel's nreps==1 fast path never touches scratch)."""
    _flash_bwd_dkv_kernel_resident(q_ref, k_ref, v_ref, do_ref, o_ref,
                                   lse_ref, dk_ref, dv_ref, None, None,
                                   **kw)


def _dkv_resident_scratch(reps: int, block_k: int, d: int):
    """(kernel_fn, scratch_shapes) for the resident dkv dispatch."""
    if reps == 1:
        return _dkv_resident_nogroup, []
    return _flash_bwd_dkv_kernel_resident, [
        pltpu.VMEM((block_k, d), jnp.float32),
        pltpu.VMEM((block_k, d), jnp.float32)]


def _fwd_scratch(block_q, d):
    return [pltpu.VMEM((block_q, _LSE_LANES), jnp.float32),   # m
            pltpu.VMEM((block_q, _LSE_LANES), jnp.float32),   # l
            pltpu.VMEM((block_q, d), jnp.float32)]            # acc


def _kv_head_of(h: int, hkv: int):
    """Zero-copy GQA (VERDICT r4 next-step #5): map the flattened (batch,
    query-head) grid index onto the (batch, kv-head) K/V array — query head
    hq reads kv head hq·hkv//h. No repeated K/V ever materializes; with
    h == hkv this is the identity."""
    reps = h // hkv
    if reps == 1:
        return lambda g: g
    return lambda g: (g // h) * hkv + (g % h) // reps


def _lane_of(reps: int):
    """Packed-layout head→kv-lane-block map; identity when reps == 1 so
    the MHA path keeps div-free index maps."""
    if reps == 1:
        return lambda h: h
    return lambda h: h // reps


def _flash_forward_streamed(q, k, v, causal, scale, block_q, block_k, interpret,
                            kv_len=None):
    b, h, t, d = q.shape
    hkv, tk = k.shape[1], k.shape[2]
    kv_of = _kv_head_of(h, hkv)
    grid = (b * h, pl.cdiv(t, block_q), pl.cdiv(tk, block_k))
    qr = q.reshape(b * h, t, d)
    kr = k.reshape(b * hkv, tk, d)
    vr = v.reshape(b * hkv, tk, d)
    kernel = functools.partial(_flash_kernel, causal=causal, scale=scale,
                               kv_len=kv_len)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda g, i, kb: (g, i, 0)),
            pl.BlockSpec((None, block_k, d),
                         lambda g, i, kb: (kv_of(g), kb, 0)),
            pl.BlockSpec((None, block_k, d),
                         lambda g, i, kb: (kv_of(g), kb, 0)),
        ],
        out_specs=(
            pl.BlockSpec((None, block_q, d), lambda g, i, kb: (g, i, 0)),
            pl.BlockSpec((None, block_q, _LSE_LANES),
                         lambda g, i, kb: (g, i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, t, _LSE_LANES), jnp.float32),
        ),
        scratch_shapes=_fwd_scratch(block_q, d),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * t * tk * d // (2 if causal else 1),
            bytes_accessed=(qr.size + kr.size + vr.size) * q.dtype.itemsize,
            transcendentals=b * h * t * tk),
    )(qr, kr, vr)
    return out.reshape(b, h, t, d), lse   # lse: [b·h, t, _LSE_LANES]


def _flash_backward_streamed(q, k, v, do, o, lse, causal, scale, block_q, block_k,
                    interpret, kv_len=None):
    b, h, t, d = q.shape
    hkv, tk = k.shape[1], k.shape[2]
    reps = h // hkv
    kv_of = _kv_head_of(h, hkv)
    bh = b * h
    qr = q.reshape(bh, t, d)
    kr, vr = k.reshape(b * hkv, tk, d), v.reshape(b * hkv, tk, d)
    dor, outr = do.reshape(bh, t, d), o.reshape(bh, t, d)
    lser = lse                                    # [bh, t, _LSE_LANES]
    # dq grid: (bh, qi, kb) — k streamed innermost (q-side blocks pinned).
    q_pin = pl.BlockSpec((None, block_q, d), lambda g, i, kb: (g, i, 0))
    k_str = pl.BlockSpec((None, block_k, d),
                         lambda g, i, kb: (kv_of(g), kb, 0))
    lse_pin = pl.BlockSpec((None, block_q, _LSE_LANES),
                           lambda g, i, kb: (g, i, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, causal=causal, scale=scale,
                          kv_len=kv_len),
        grid=(bh, pl.cdiv(t, block_q), pl.cdiv(tk, block_k)),
        in_specs=[q_pin, k_str, k_str, q_pin, q_pin, lse_pin],
        out_specs=q_pin,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, outr, lser)

    # dkv grid: (b·hkv, kj, qx) — qx is the flattened (rep, q-block) sweep
    # (k-blocks pinned; dk/dv accumulate across ALL query heads this kv
    # head serves). reps==1 keeps the original identity maps (no per-step
    # div/mod in the index computation).
    nqb = pl.cdiv(t, block_q)

    def q_head(g, qx):
        return (g // hkv) * h + (g % hkv) * reps + qx // nqb

    k_pin = pl.BlockSpec((None, block_k, d), lambda g, j, qx: (g, j, 0))
    if reps == 1:
        q_str = pl.BlockSpec((None, block_q, d),
                             lambda g, j, qx: (g, qx, 0))
        lse_str = pl.BlockSpec((None, block_q, _LSE_LANES),
                               lambda g, j, qx: (g, qx, 0))
    else:
        q_str = pl.BlockSpec((None, block_q, d),
                             lambda g, j, qx: (q_head(g, qx), qx % nqb, 0))
        lse_str = pl.BlockSpec((None, block_q, _LSE_LANES),
                               lambda g, j, qx: (q_head(g, qx), qx % nqb, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, causal=causal, scale=scale,
                          nqb=nqb if reps > 1 else 0, kv_len=kv_len),
        grid=(b * hkv, pl.cdiv(tk, block_k), reps * nqb),
        in_specs=[q_str, k_pin, k_pin, q_str, q_str, lse_str],
        out_specs=(k_pin, k_pin),
        out_shape=(jax.ShapeDtypeStruct((b * hkv, tk, d), k.dtype),
                   jax.ShapeDtypeStruct((b * hkv, tk, d), v.dtype)),
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, outr, lser)
    return (dq.reshape(q.shape), dk.reshape(k.shape), dv.reshape(v.shape))



# --------------------------------------------------------------------
# Resident-KV variants: the whole K/V for one (batch, head) lives in
# VMEM and the kernel loops k-blocks internally, letting causal grids
# skip above-diagonal blocks from the SCHEDULE (not just the compute)
# — measured ~7% faster than the streamed kernels at bench shapes.
# Only legal while K/V fit VMEM; _RESIDENT_MAX_T gates the dispatch
# (t=8192 OOMs v5e VMEM, t=4096 fits with headroom).
# --------------------------------------------------------------------

def _flash_kernel_resident(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                  causal: bool, scale: float, qi_axis: int = 1,
                  kv_len: Optional[int] = None):
    """One grid cell: q-block [Bq, D] against the full K/V [T, D] in VMEM,
    streamed in block_k chunks through the online-softmax recurrence. Also
    writes the log-sum-exp rows the backward kernels reconstruct p from.
    ``qi_axis`` is which grid axis carries the q-block index (1 for the
    [B·H, T, D] layout's (bh, i) grid, 2 for the packed [B, T, H·D]
    layout's (b, h, i) grid)."""
    bq, d = q_ref.shape
    t = k_ref.shape[0]
    qi = pl.program_id(qi_axis)
    # Matmul inputs stay in their storage dtype (bf16): bf16×bf16 products
    # are exact in the MXU's f32 accumulator, so this loses nothing over
    # upcast-then-dot — and doesn't rely on Mosaic folding converts back
    # out of an f32 matmul (measured parity on v5e: the fold does happen
    # today, but it's the compiler's choice, not the kernel's contract).
    # Softmax math (max/exp/normalizer) runs in f32; p casts back for the
    # PV matmul.
    q = q_ref[:]

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    a0 = jnp.zeros((bq, d), jnp.float32)

    if causal:
        # Only k-blocks touching or below the diagonal contribute.
        num_kb = pl.cdiv((qi + 1) * bq, block_k)
    else:
        num_kb = pl.cdiv(t, block_k)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [Bq, Bk]
        s = _mask_s(s, qi, bq, kb, block_k, causal, kv_len)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, a0))
    l_safe = jnp.where(l > 0, l, 1.0)
    o_ref[:] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[:] = jnp.broadcast_to(m + jnp.log(l_safe), (bq, _LSE_LANES))


def _flash_bwd_dq_kernel_resident(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref,
                         *, block_k: int, causal: bool, scale: float,
                         qi_axis: int = 1, kv_len: Optional[int] = None):
    """dq for one q-block: recompute p from (q, k, lse) per k-block —
    ds = p·(dpᵀ−D); dq += ds·k·scale. No T×T buffer ever materializes."""
    bq, d = q_ref.shape
    t = k_ref.shape[0]
    qi = pl.program_id(qi_axis)
    # bf16 matmul operands / f32 accumulation + f32 softmax math — see the
    # forward kernel's dtype note.
    q = q_ref[:]
    do = do_ref[:]
    D = jnp.sum(do.astype(jnp.float32) * o_ref[:].astype(jnp.float32),
                axis=-1, keepdims=True)                  # [Bq, 1]
    lse = lse_ref[:, 0:1]                                # [Bq, 1]
    num_kb = pl.cdiv((qi + 1) * bq, block_k) if causal else pl.cdiv(
        t, block_k)

    def body(kb, dq):
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _mask_s(s, qi, bq, kb, block_k, causal, kv_len)
        p = jnp.exp(s - lse)                              # exact softmax
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - D)).astype(k_blk.dtype)
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    dq = jax.lax.fori_loop(0, num_kb, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel_resident(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                          dk_ref, dv_ref, dk_scr, dv_scr, *, block_q: int,
                          causal: bool, scale: float, qi_axis: int = 1,
                          kv_len: Optional[int] = None):
    """dk/dv for one k-block: iterate q-blocks (from the diagonal down when
    causal): dv += pᵀ·do; dk += dsᵀ·q·scale.

    GQA: the grid carries a ``rep`` axis INSIDE the k-block axis (size 1
    without grouping); each rep step streams in one of the query heads this
    kv head serves, and dk/dv accumulate in VMEM scratch across the sweep,
    flushing on the last rep."""
    bk, d = k_ref.shape
    t = q_ref.shape[0]
    kj = pl.program_id(qi_axis)
    rep = pl.program_id(qi_axis + 1)
    nreps = pl.num_programs(qi_axis + 1)   # static (grid is static)

    if nreps > 1:
        @pl.when(rep == 0)
        def _init():
            dk_scr[:] = jnp.zeros_like(dk_scr)
            dv_scr[:] = jnp.zeros_like(dv_scr)
    # bf16 matmul operands / f32 accumulation + f32 softmax math — see the
    # forward kernel's dtype note.
    k_blk = k_ref[:]
    v_blk = v_ref[:]
    num_qb = pl.cdiv(t, block_q)
    qb0 = (kj * bk) // block_q if causal else 0

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[pl.ds(qb * block_q, block_q), :]
        do = do_ref[pl.ds(qb * block_q, block_q), :]
        o = o_ref[pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[pl.ds(qb * block_q, block_q), 0:1]  # [Bq, 1]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _mask_s(s, qb, block_q, kj, bk, causal, kv_len)
        p = jnp.exp(s - lse)                              # [Bq, Bk]
        dv_new = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        D = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)
        ds = (p * (dp - D)).astype(q.dtype)
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        return dk_new, dv_new

    if nreps == 1:
        # MHA / reps==1 fast path: register accumulation, one flush — no
        # scratch round-trips (measured ~4 MFU pts on the r5 LLM bench
        # when the grouped path ran unconditionally).
        zeros = jnp.zeros((bk, d), jnp.float32)
        dk, dv = jax.lax.fori_loop(qb0, num_qb, body, (zeros, zeros))
        dk_ref[:] = dk.astype(dk_ref.dtype)
        dv_ref[:] = dv.astype(dv_ref.dtype)
        return

    dk, dv = jax.lax.fori_loop(qb0, num_qb, body,
                               (dk_scr[:], dv_scr[:]))
    dk_scr[:] = dk
    dv_scr[:] = dv

    @pl.when(rep == nreps - 1)
    def _finalize():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _flash_forward_resident(q, k, v, causal, scale, block_q, block_k, interpret,
                            kv_len=None):
    b, h, t, d = q.shape
    hkv, tk = k.shape[1], k.shape[2]
    kv_of = _kv_head_of(h, hkv)
    grid = (b * h, pl.cdiv(t, block_q))
    qr = q.reshape(b * h, t, d)
    kr = k.reshape(b * hkv, tk, d)
    vr = v.reshape(b * hkv, tk, d)
    kernel = functools.partial(_flash_kernel_resident, block_k=block_k,
                               causal=causal, scale=scale, kv_len=kv_len)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, i: (kv_of(bh), 0, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, i: (kv_of(bh), 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((None, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((None, block_q, _LSE_LANES), lambda bh, i: (bh, i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, t, _LSE_LANES), jnp.float32),
        ),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * t * tk * d // (2 if causal else 1),
            bytes_accessed=(qr.size + kr.size + vr.size) * q.dtype.itemsize,
            transcendentals=b * h * t * tk),
    )(qr, kr, vr)
    return out.reshape(b, h, t, d), lse   # lse: [b·h, t, _LSE_LANES]


def _flash_backward_resident(q, k, v, do, o, lse, causal, scale, block_q, block_k,
                    interpret, kv_len=None):
    b, h, t, d = q.shape
    hkv, tk = k.shape[1], k.shape[2]
    reps = h // hkv
    kv_of = _kv_head_of(h, hkv)
    bh = b * h
    qr = q.reshape(bh, t, d)
    kr, vr = k.reshape(b * hkv, tk, d), v.reshape(b * hkv, tk, d)
    dor, outr = do.reshape(bh, t, d), o.reshape(bh, t, d)
    lser = lse                                    # [bh, t, _LSE_LANES]
    q_spec = pl.BlockSpec((None, block_q, d), lambda g, i: (g, i, 0))
    kv_full = pl.BlockSpec((None, tk, d), lambda g, i: (kv_of(g), 0, 0))
    lse_blk = pl.BlockSpec((None, block_q, _LSE_LANES), lambda g, i: (g, i, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel_resident, block_k=block_k,
                          causal=causal, scale=scale, kv_len=kv_len),
        grid=(bh, pl.cdiv(t, block_q)),
        in_specs=[q_spec, kv_full, kv_full, q_spec, q_spec, lse_blk],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        interpret=interpret,
    )(qr, kr, vr, dor, outr, lser)

    # dkv grid: (b·hkv, kj, rep) — rep streams in, one at a time, the query
    # heads this kv head serves; dk/dv accumulate in scratch across them.
    def q_head(g, r):
        return (g // hkv) * h + (g % hkv) * reps + r

    q_full = pl.BlockSpec((None, t, d), lambda g, j, r: (q_head(g, r), 0, 0))
    lse_full = pl.BlockSpec((None, t, _LSE_LANES),
                            lambda g, j, r: (q_head(g, r), 0, 0))
    k_spec = pl.BlockSpec((None, block_k, d), lambda g, j, r: (g, j, 0))

    dkv_kernel, dkv_scratch = _dkv_resident_scratch(reps, block_k, d)
    dk, dv = pl.pallas_call(
        functools.partial(dkv_kernel, block_q=block_q,
                          causal=causal, scale=scale, kv_len=kv_len),
        grid=(b * hkv, pl.cdiv(tk, block_k), reps),
        in_specs=[q_full, k_spec, k_spec, q_full, q_full, lse_full],
        out_specs=(k_spec, k_spec),
        out_shape=(jax.ShapeDtypeStruct((b * hkv, tk, d), k.dtype),
                   jax.ShapeDtypeStruct((b * hkv, tk, d), v.dtype)),
        scratch_shapes=dkv_scratch,
        interpret=interpret,
    )(qr, kr, vr, dor, outr, lser)
    return (dq.reshape(q.shape), dk.reshape(k.shape), dv.reshape(v.shape))



# One (batch, head)'s K/V must fit VMEM for the resident variants. The
# budget is in BYTES, not sequence length: VMEM use scales with
# tk·d·itemsize, so a fixed max-T gate (round 3) would OOM below it for
# head_dim>128 or f32 inputs. Calibrated on v5e at the measured boundary —
# t=4096·d=128·bf16 (1 MiB per tensor) fits with headroom, t=8192 OOMs.
_RESIDENT_KV_BYTES = 4096 * 128 * 2


def _resident_fits(tk: int, d: int, dtype) -> bool:
    return tk * d * jnp.dtype(dtype).itemsize <= _RESIDENT_KV_BYTES


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret,
                   kv_len=None):
    if _resident_fits(k.shape[2], k.shape[3], k.dtype):
        return _flash_forward_resident(q, k, v, causal, scale, block_q,
                                       block_k, interpret, kv_len)
    return _flash_forward_streamed(q, k, v, causal, scale, block_q,
                                   block_k, interpret, kv_len)


def _flash_backward(q, k, v, do, o, lse, causal, scale, block_q, block_k,
                    interpret, kv_len=None):
    if _resident_fits(k.shape[2], k.shape[3], k.dtype):
        return _flash_backward_resident(q, k, v, do, o, lse, causal, scale,
                                        block_q, block_k, interpret, kv_len)
    return _flash_backward_streamed(q, k, v, do, o, lse, causal, scale,
                                    block_q, block_k, interpret, kv_len)


def _flash_forward_packed(q, k, v, heads, causal, scale, block_q, block_k,
                          interpret):
    if _resident_fits(k.shape[1], q.shape[2] // heads, k.dtype):
        return _flash_forward_packed_resident(q, k, v, heads, causal, scale,
                                              block_q, block_k, interpret)
    return _flash_forward_packed_streamed(q, k, v, heads, causal, scale,
                                          block_q, block_k, interpret)


def _flash_backward_packed(q, k, v, do, o, lse, heads, causal, scale,
                           block_q, block_k, interpret):
    if _resident_fits(k.shape[1], q.shape[2] // heads, k.dtype):
        return _flash_backward_packed_resident(
            q, k, v, do, o, lse, heads, causal, scale, block_q, block_k,
            interpret)
    return _flash_backward_packed_streamed(
        q, k, v, do, o, lse, heads, causal, scale, block_q, block_k,
        interpret)


def _flash_forward_packed_resident(q, k, v, heads, causal, scale, block_q, block_k,
                          interpret):
    """Forward over the packed [B, T, H·D] layout: grid (b, h, i) with the
    head carried as a lane offset (block index h on the last dim) — no
    [B, H, T, D] transpose ever materializes. Same kernel body. GQA: K/V
    are packed [B, T, Hkv·D]; query head h reads kv lane-block h·hkv//h."""
    b, t, hd = q.shape
    tk = k.shape[1]
    d = hd // heads
    reps = hd // k.shape[2]
    lane = _lane_of(reps)
    grid = (b, heads, pl.cdiv(t, block_q))
    kernel = functools.partial(_flash_kernel_resident, block_k=block_k,
                               causal=causal, scale=scale, qi_axis=2)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bi, h, i: (bi, i, h)),
            pl.BlockSpec((None, tk, d), lambda bi, h, i: (bi, 0, lane(h))),
            pl.BlockSpec((None, tk, d), lambda bi, h, i: (bi, 0, lane(h))),
        ],
        out_specs=(
            pl.BlockSpec((None, block_q, d), lambda bi, h, i: (bi, i, h)),
            pl.BlockSpec((None, None, block_q, _LSE_LANES),
                         lambda bi, h, i: (bi, h, i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b, t, hd), q.dtype),
            jax.ShapeDtypeStruct((b, heads, t, _LSE_LANES), jnp.float32),
        ),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * b * heads * t * tk * d // (2 if causal else 1),
            bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=b * heads * t * tk),
    )(q, k, v)
    return out, lse


def _flash_backward_packed_resident(q, k, v, do, o, lse, heads, causal, scale,
                           block_q, block_k, interpret):
    b, t, hd = q.shape
    tk = k.shape[1]
    d = hd // heads
    hkv = k.shape[2] // d
    reps = heads // hkv
    lane = _lane_of(reps)
    q_spec = pl.BlockSpec((None, block_q, d), lambda bi, h, i: (bi, i, h))
    kv_full = pl.BlockSpec((None, tk, d),
                           lambda bi, h, i: (bi, 0, lane(h)))
    lse_blk = pl.BlockSpec((None, None, block_q, _LSE_LANES),
                           lambda bi, h, i: (bi, h, i, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel_resident, block_k=block_k,
                          causal=causal, scale=scale, qi_axis=2),
        grid=(b, heads, pl.cdiv(t, block_q)),
        in_specs=[q_spec, kv_full, kv_full, q_spec, q_spec, lse_blk],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, t, hd), q.dtype),
        interpret=interpret,
    )(q, k, v, do, o, lse)

    # dkv grid: (b, hkv, kj, rep) — rep streams the query heads this kv
    # head serves; dk/dv accumulate in scratch (see the kernel docstring).
    q_full = pl.BlockSpec((None, t, d),
                          lambda bi, hk, j, r: (bi, 0, hk * reps + r))
    lse_full = pl.BlockSpec((None, None, t, _LSE_LANES),
                            lambda bi, hk, j, r: (bi, hk * reps + r, 0, 0))
    k_spec = pl.BlockSpec((None, block_k, d),
                          lambda bi, hk, j, r: (bi, j, hk))

    dkv_kernel, dkv_scratch = _dkv_resident_scratch(reps, block_k, d)
    dk, dv = pl.pallas_call(
        functools.partial(dkv_kernel, block_q=block_q,
                          causal=causal, scale=scale, qi_axis=2),
        grid=(b, hkv, pl.cdiv(tk, block_k), reps),
        in_specs=[q_full, k_spec, k_spec, q_full, q_full, lse_full],
        out_specs=(k_spec, k_spec),
        out_shape=(jax.ShapeDtypeStruct((b, tk, hkv * d), k.dtype),
                   jax.ShapeDtypeStruct((b, tk, hkv * d), v.dtype)),
        scratch_shapes=dkv_scratch,
        interpret=interpret,
    )(q, k, v, do, o, lse)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret,
           kv_len=None):
    out, _ = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                            interpret, kv_len)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               kv_len=None):
    out, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                              interpret, kv_len)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, kv_len,
               residuals, g):
    q, k, v, out, lse = residuals
    return _flash_backward(q, k, v, g, out, lse, causal, scale, block_q,
                           block_k, interpret, kv_len)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _flash_forward_packed_streamed(q, k, v, heads, causal, scale, block_q, block_k,
                          interpret):
    """Forward over the packed [B, T, H·D] layout: grid (b, h, i, kb) with
    the head carried as a lane offset (block index h on the last dim) — no
    [B, H, T, D] transpose ever materializes. Same streamed kernel body."""
    b, t, hd = q.shape
    tk = k.shape[1]
    d = hd // heads
    reps = hd // k.shape[2]
    lane = _lane_of(reps)
    grid = (b, heads, pl.cdiv(t, block_q), pl.cdiv(tk, block_k))
    kernel = functools.partial(_flash_kernel, causal=causal, scale=scale,
                               qi_axis=2)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d),
                         lambda bi, h, i, kb: (bi, i, h)),
            pl.BlockSpec((None, block_k, d),
                         lambda bi, h, i, kb: (bi, kb, lane(h))),
            pl.BlockSpec((None, block_k, d),
                         lambda bi, h, i, kb: (bi, kb, lane(h))),
        ],
        out_specs=(
            pl.BlockSpec((None, block_q, d),
                         lambda bi, h, i, kb: (bi, i, h)),
            pl.BlockSpec((None, None, block_q, _LSE_LANES),
                         lambda bi, h, i, kb: (bi, h, i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b, t, hd), q.dtype),
            jax.ShapeDtypeStruct((b, heads, t, _LSE_LANES), jnp.float32),
        ),
        scratch_shapes=_fwd_scratch(block_q, d),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * b * heads * t * tk * d // (2 if causal else 1),
            bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=b * heads * t * tk),
    )(q, k, v)
    return out, lse


def _flash_backward_packed_streamed(q, k, v, do, o, lse, heads, causal, scale,
                           block_q, block_k, interpret):
    b, t, hd = q.shape
    tk = k.shape[1]
    d = hd // heads
    hkv = k.shape[2] // d
    reps = heads // hkv
    # dq grid: (b, h, qi, kb) — k streamed innermost.
    q_pin = pl.BlockSpec((None, block_q, d),
                         lambda bi, h, i, kb: (bi, i, h))
    lane = _lane_of(reps)
    k_str = pl.BlockSpec((None, block_k, d),
                         lambda bi, h, i, kb: (bi, kb, lane(h)))
    lse_pin = pl.BlockSpec((None, None, block_q, _LSE_LANES),
                           lambda bi, h, i, kb: (bi, h, i, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, causal=causal, scale=scale,
                          qi_axis=2),
        grid=(b, heads, pl.cdiv(t, block_q), pl.cdiv(tk, block_k)),
        in_specs=[q_pin, k_str, k_str, q_pin, q_pin, lse_pin],
        out_specs=q_pin,
        out_shape=jax.ShapeDtypeStruct((b, t, hd), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, o, lse)

    # dkv grid: (b, hkv, kj, qx) — qx flattens (rep, q-block), q-side
    # streamed innermost; dk/dv accumulate across every query head this
    # kv head serves. reps==1 keeps identity (div/mod-free) index maps.
    nqb = pl.cdiv(t, block_q)
    k_pin = pl.BlockSpec((None, block_k, d),
                         lambda bi, hk, j, qx: (bi, j, hk))
    if reps == 1:
        q_str = pl.BlockSpec((None, block_q, d),
                             lambda bi, hk, j, qx: (bi, qx, hk))
        lse_str = pl.BlockSpec((None, None, block_q, _LSE_LANES),
                               lambda bi, hk, j, qx: (bi, hk, qx, 0))
    else:
        q_str = pl.BlockSpec((None, block_q, d),
                             lambda bi, hk, j, qx:
                             (bi, qx % nqb, hk * reps + qx // nqb))
        lse_str = pl.BlockSpec((None, None, block_q, _LSE_LANES),
                               lambda bi, hk, j, qx:
                               (bi, hk * reps + qx // nqb, qx % nqb, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, causal=causal, scale=scale,
                          qi_axis=2, nqb=nqb if reps > 1 else 0),
        grid=(b, hkv, pl.cdiv(tk, block_k), reps * nqb),
        in_specs=[q_str, k_pin, k_pin, q_str, q_str, lse_str],
        out_specs=(k_pin, k_pin),
        out_shape=(jax.ShapeDtypeStruct((b, tk, hkv * d), k.dtype),
                   jax.ShapeDtypeStruct((b, tk, hkv * d), v.dtype)),
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, o, lse)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_packed(q, k, v, heads, causal, scale, block_q, block_k,
                  interpret):
    out, _ = _flash_forward_packed(q, k, v, heads, causal, scale, block_q,
                                   block_k, interpret)
    return out


def _flash_packed_fwd(q, k, v, heads, causal, scale, block_q, block_k,
                      interpret):
    out, lse = _flash_forward_packed(q, k, v, heads, causal, scale,
                                     block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_packed_bwd(heads, causal, scale, block_q, block_k, interpret,
                      residuals, g):
    q, k, v, out, lse = residuals
    return _flash_backward_packed(q, k, v, g, out, lse, heads, causal,
                                  scale, block_q, block_k, interpret)


_flash_packed.defvjp(_flash_packed_fwd, _flash_packed_bwd)


def _fit_block(limit: int, t: int) -> int:
    """Largest block ≤ limit that divides ``t`` and is a multiple of the
    16-row sublane tile; 0 if none exists (ragged ``t``)."""
    b = min(limit, t)
    b -= b % 16
    while b >= 16 and t % b:
        b -= 16
    return b if b >= 16 else 0


def _plan_dispatch(t, tk, block_q, block_k, causal):
    """Shared kernel-dispatch policy for both layouts:
    ``("kernel", bq, bk, None)`` — tile-legal dividing blocks exist;
    ``("pad", bq, bk, t_pad)`` — causal self-attention, zero-pad the seq
    (end-padded keys sit above every real query's diagonal, so the causal
    mask hides them for free);
    ``("pad_masked", bq, bk, (t_pad, tk_pad, kv_len))`` — any other
    ragged lengths (non-causal, or cross q/k): q and K/V zero-pad
    independently to tile-legal block multiples and the kernels mask the
    padded keys via the static ``kv_len`` (the BENCH_r02 block-shape
    constraint used to send these shapes to the reference fallback — the
    T×T score materialization — instead).
    """
    bq, bk = _fit_block(block_q, t), _fit_block(block_k, tk)
    if bq and bk:
        return ("kernel", bq, bk, None)
    bq = min(max(16, block_q - block_q % 16), t + ((-t) % 16))
    bk = min(max(16, block_k - block_k % 16), tk + ((-tk) % 16))
    if causal and t == tk:
        import math
        t_pad = t + ((-t) % math.lcm(bq, bk))
        return ("pad", bq, bk, t_pad)
    t_pad = t + ((-t) % bq)
    tk_pad = tk + ((-tk) % bk)
    return ("pad_masked", bq, bk, (t_pad, tk_pad, tk))


def _warn_fallback(reason: str) -> None:
    """One warning per distinct reason when a TPU run leaves the kernel
    path — the reference fallback materializes the T×T score matrix, an
    OOM/perf cliff on long sequences that should never be silent."""
    import warnings

    if reason not in _warned:
        _warned.add(reason)
        warnings.warn(
            f"flash_attention: falling back to reference attention "
            f"({reason}); the full score matrix will materialize",
            stacklevel=3)


_warned: set = set()


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: int = 256, block_k: int = 256,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Fused attention over ``[batch, heads, seq, head_dim]``.

    Dispatch: the pallas kernel on TPU backends (or when ``interpret=True``
    forces the pallas interpreter — how CPU tests cover the kernel), the
    pure-JAX reference elsewhere. Odd shapes stay on the kernel path:
    causal self-attention with a sequence length that doesn't divide the
    block size is zero-padded up to the next block boundary (end-padded
    keys sit above the diagonal for every real query, so the causal mask
    already excludes them); other ragged seq lengths zero-pad q and K/V
    independently with the padded keys masked in-kernel (static
    ``kv_len``); a head_dim off the 8-row sublane tile zero-pads the
    feature dim (zero k-dims add nothing to scores, zero v-columns are
    sliced off). The reference only runs on non-TPU backends.

    Default blocks are 256: 128² score tiles are MXU-pipeline-latency
    dominated (measured 14.5→9.7 ms per layer fwd+bwd going 128→256 at
    b32·h8·t512·d128 on v5e; 512 measured equal to 256 with more VMEM
    pressure).

    GQA is zero-copy: K/V may carry ``heads // reps`` heads — the kernels'
    index maps route query head h to kv head h·hkv/h, and the dk/dv grids
    group by kv head, so no repeated K/V ever materializes in HBM.
    """
    d = q.shape[-1]
    scale = d ** -0.5 if scale is None else scale
    t, tk = q.shape[2], k.shape[2]
    if q.shape[1] % k.shape[1]:
        raise ValueError(
            f"query heads {q.shape[1]} not a multiple of kv heads "
            f"{k.shape[1]}")
    if interpret is None:
        on_tpu = jax.default_backend() == "tpu"
        if not on_tpu:
            return reference_attention(q, k, v, causal, scale)
        interpret = False
    if d % 8:
        # Head dim off the 8-row sublane tile: zero-pad the feature dim
        # (extra k dims add 0 to every score; extra v dims emit zero
        # output columns, sliced off — scale was already computed from
        # the REAL d above) and stay on the kernel path.
        widths = ((0, 0), (0, 0), (0, 0), (0, (-d) % 8))
        return flash_attention(
            jnp.pad(q, widths), jnp.pad(k, widths), jnp.pad(v, widths),
            causal=causal, scale=scale, block_q=block_q, block_k=block_k,
            interpret=interpret)[..., :d]
    # Blocks must divide the seq dims AND be sublane-tile-legal: the
    # in-kernel pl.ds(kb*block, block) K/V slices need block to be a
    # multiple of the sublane tile (8 for f32, 16 for bf16 — 16 covers
    # both), else Mosaic rejects the unaligned slice even when the block
    # equals the array dim. _plan_dispatch shrinks to the largest dividing
    # tile-legal block before resorting to padding, so e.g. t=384 runs
    # the kernel unpadded at block 192 rather than padding to 512; the
    # pad paths re-bound blocks by the padded length so short sequences
    # don't pay for a full default-sized block (t=8 pads to 16, not 128).
    plan, bq, bk, extra = _plan_dispatch(t, tk, block_q, block_k, causal)
    if plan == "kernel":
        return _flash(q, k, v, causal, scale, bq, bk, interpret, None)
    if plan == "pad":
        widths = ((0, 0), (0, 0), (0, extra - t), (0, 0))
        qp, kp, vp = (jnp.pad(x, widths) for x in (q, k, v))
        out = _flash(qp, kp, vp, causal, scale, bq, bk, interpret, None)
        return out[:, :, :t, :]
    t_pad, tk_pad, kv_len = extra
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, t_pad - t), (0, 0)))
    kvw = ((0, 0), (0, 0), (0, tk_pad - tk), (0, 0))
    out = _flash(qp, jnp.pad(k, kvw), jnp.pad(v, kvw), causal, scale,
                 bq, bk, interpret, kv_len if tk_pad != tk else None)
    return out[:, :, :t, :]


def flash_attention_packed(q: jax.Array, k: jax.Array, v: jax.Array,
                           heads: int, causal: bool = True,
                           scale: Optional[float] = None,
                           block_q: int = 256, block_k: int = 256,
                           interpret: Optional[bool] = None) -> jax.Array:
    """Fused attention over the packed ``[batch, seq, heads·head_dim]``
    layout — the projection output's natural shape. The kernel reads each
    head as a lane offset (grid ``(b, h, i)``), so the ``[B, H, T, D]``
    transpose+copy the classic layout forces never materializes; the
    profiled win on the Llama bench is ~5% of step time. Requires
    ``head_dim`` to be a multiple of 128 (lane-tile alignment for the
    per-head slices); otherwise use :func:`flash_attention`. GQA is
    zero-copy here too: K/V may be packed ``[B, T, Hkv·D]`` with
    ``heads % Hkv == 0`` — query head h reads kv lane-block h·Hkv/heads."""
    b, t, hd = q.shape
    tk = k.shape[1]
    if hd % heads:
        raise ValueError(
            f"packed dim {hd} is not divisible by heads={heads}")
    d = hd // heads
    if k.shape[2] % d or heads % (k.shape[2] // d):
        raise ValueError(
            f"packed kv dim {k.shape[2]} is not a head-multiple of "
            f"head_dim {d} dividing heads={heads}")
    if k.shape != v.shape:
        # reps is derived from k; a mixed narrow-k/wide-v call (the
        # pre-GQA convention) would silently read wrong v lane blocks.
        raise ValueError(f"k {k.shape} and v {v.shape} must match")
    scale = d ** -0.5 if scale is None else scale

    def unpacked_fallback():
        def to4(x):
            return x.reshape(b, -1, x.shape[2] // d, d).transpose(0, 2, 1, 3)
        out = flash_attention(to4(q), to4(k), to4(v), causal=causal,
                              scale=scale, block_q=block_q, block_k=block_k,
                              interpret=interpret)
        return out.transpose(0, 2, 1, 3).reshape(b, t, hd)

    if interpret is None:
        if jax.default_backend() != "tpu":
            return unpacked_fallback()
        interpret = False
    if d % 128:
        _warn_fallback(
            f"packed layout needs head_dim % 128 == 0, got {d}")
        return unpacked_fallback()
    plan, bq, bk, extra = _plan_dispatch(t, tk, block_q, block_k, causal)
    if plan == "kernel":
        return _flash_packed(q, k, v, heads, causal, scale, bq, bk,
                             interpret)
    if plan == "pad_masked":
        # Ragged non-causal / cross lengths: route through the classic
        # layout, whose pad+mask path keeps the pallas kernel (the packed
        # kernels don't carry the kv mask — one transpose beats a T×T
        # reference materialization).
        return unpacked_fallback()
    widths = ((0, 0), (0, extra - t), (0, 0))
    qp, kp, vp = (jnp.pad(x, widths) for x in (q, k, v))
    out = _flash_packed(qp, kp, vp, heads, causal, scale, bq, bk, interpret)
    return out[:, :t, :]


# --------------------------------------------------------------------
# Flash decoding: the serving plane's attention (tony_tpu.serve). One
# small q-block (the engine's fixed row block — a sublane tile of new
# tokens) attends over a long cached K/V buffer, streamed in k-blocks
# through the same online-softmax recurrence as the training kernels.
# Forward-only (no vjp: serving never differentiates), masked by ABSOLUTE
# positions (each row carries its own position — continuous batching puts
# rows of different sequences, at different depths, in one launch).
#
# Numerics contract (the serve plane's decode-vs-prefill bit pin rides on
# it): the pallas kernel and the pure-XLA fallback share one mask/update
# expression (`_decode_mask_update`) and issue the same f32 dots in the
# same per-block order, so they are bit-identical; and every op is
# row-independent, so a row computes the same bits whether it rides a
# prefill block, a decode block, or a differently-joined batch (the
# engine keeps all row counts at sublane-tile multiples — single-row
# GEMV paths are the one place XLA CPU breaks row invariance).
#
# The speculative lane (tony_tpu.serve.spec) leans on the same contract
# from a third direction: its one-launch k-token verification is a
# decode-shaped call whose q-block carries k+1 REAL rows at consecutive
# positions p0..p0+k (the engine scatters all k+1 candidate KV rows into
# the buffer first, so row j attends the draft rows below it). Because
# each row's mask is its own absolute position and every op is
# row-independent, verify row j is bit-identical to the plain decode row
# at position p0+j — which is exactly what makes greedy accept/reject
# reproduce sequential greedy decode bit for bit, with rejected rows
# never read (they sit above every surviving row's position).
# --------------------------------------------------------------------


def _decode_mask_update(s, q_pos, k_pos, m, l):
    """One online-softmax block step, shared verbatim by the pallas
    kernel and the XLA fallback: mask scores by absolute position
    (``k_pos <= q_pos`` — causal over the cache, which also hides
    unwritten/garbage buffer tail positions), then fold the block into
    the running (m, l) state. All f32; broadcasting carries the leading
    batch dims of whichever caller."""
    s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    return p, alpha, m_new, l_new


def _decode_xla(q, k, v, q_positions, scale, block_k):
    """Pure-XLA flash-decode fallback: fori_loop over k-blocks of the
    cache, grouped [b, hkv, g·t, d] so GQA query heads batch onto their
    kv head exactly like the kernel's head map."""
    b, h, t, d = q.shape
    hkv, ctx = k.shape[1], k.shape[2]
    g = h // hkv
    qf = q.astype(jnp.float32).reshape(b, hkv, g * t, d)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    # [b, 1, g·t, 1] absolute position per row (the g query heads of one
    # kv head share their rows' positions).
    q_pos = jnp.broadcast_to(
        q_positions.astype(jnp.int32)[:, None, None, :],
        (b, hkv, g, t)).reshape(b, hkv, g * t, 1)
    nkb = ctx // block_k
    m0 = jnp.full((b, hkv, g * t, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, g * t, 1), jnp.float32)
    a0 = jnp.zeros((b, hkv, g * t, d), jnp.float32)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = jax.lax.dynamic_slice_in_dim(kf, kb * block_k, block_k, 2)
        v_blk = jax.lax.dynamic_slice_in_dim(vf, kb * block_k, block_k, 2)
        s = jax.lax.dot_general(
            qf, k_blk, (((3,), (3,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32) * scale
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 3)
        p, alpha, m_new, l_new = _decode_mask_update(s, q_pos, k_pos, m, l)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((3,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, nkb, body, (m0, l0, a0))
    out = acc / jnp.where(l > 0, l, 1.0)
    return out.reshape(b, hkv, g, t, d).reshape(b, h, t, d).astype(q.dtype)


def _decode_kernel(q_ref, k_ref, v_ref, pos_ref, o_ref, *, block_k: int,
                   scale: float):
    """One (batch, query-head) cell: q-block [t, d] against this kv
    head's full cached [ctx, d] in VMEM, k-blocks streamed through the
    shared online recurrence. Positions ride lane-replicated int32 (the
    lse layout trick — a 1-D block would squeeze illegally on Mosaic)."""
    t, d = q_ref.shape
    ctx = k_ref.shape[0]
    q = q_ref[:].astype(jnp.float32)
    q_pos = pos_ref[:, 0:1]

    m0 = jnp.full((t, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((t, 1), jnp.float32)
    a0 = jnp.zeros((t, d), jnp.float32)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        p, alpha, m_new, l_new = _decode_mask_update(s, q_pos, k_pos, m, l)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, ctx // block_k, body, (m0, l0, a0))
    o_ref[:] = (acc / jnp.where(l > 0, l, 1.0)).astype(o_ref.dtype)


def _decode_pallas(q, k, v, q_positions, scale, block_k, interpret):
    b, h, t, d = q.shape
    hkv, ctx = k.shape[1], k.shape[2]
    reps = h // hkv
    pos = jnp.broadcast_to(
        q_positions.astype(jnp.int32)[:, :, None], (b, t, _LSE_LANES))
    return pl.pallas_call(
        functools.partial(_decode_kernel, block_k=block_k, scale=scale),
        grid=(b, h),
        in_specs=[
            pl.BlockSpec((None, None, t, d), lambda bi, hi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, ctx, d),
                         lambda bi, hi: (bi, hi // reps, 0, 0)),
            pl.BlockSpec((None, None, ctx, d),
                         lambda bi, hi: (bi, hi // reps, 0, 0)),
            pl.BlockSpec((None, t, _LSE_LANES), lambda bi, hi: (bi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, t, d),
                               lambda bi, hi: (bi, hi, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * t * ctx * d,
            bytes_accessed=(k.size + v.size) * k.dtype.itemsize
            + q.size * q.dtype.itemsize,
            transcendentals=b * h * t * ctx),
    )(q, k, v, pos)


def flash_decode(q: jax.Array, k: jax.Array, v: jax.Array,
                 q_positions: jax.Array, *, scale: Optional[float] = None,
                 block_k: int = 128,
                 interpret: Optional[bool] = None) -> jax.Array:
    """Flash-decoding attention for the serving plane: a small q-block
    ``[b, h, t, d]`` (t = the engine's row block) against a cached K/V
    buffer ``[b, hkv, ctx, d]``, masked by each row's ABSOLUTE position
    (``q_positions`` int32 ``[b, t]``: key j participates in row i iff
    ``j <= q_positions[i]`` — causal over the cache, and unwritten buffer
    tail positions are excluded for free because they sit above every
    live row's position).

    Dispatch mirrors :func:`flash_attention`: the pallas kernel on TPU
    (``interpret=True`` for CPU test coverage), the pure-XLA fallback
    elsewhere — the two are bit-identical (shared
    :func:`_decode_mask_update`, same f32 dots in the same k-block
    order), which the serve tests pin. GQA is zero-copy (query head h
    reads kv head ``h·hkv/h``). Forward-only: serving never
    differentiates through the cache.
    """
    if q.ndim != 4 or k.ndim != 4:
        raise ValueError(f"flash_decode wants [b, h, t, d] q and "
                         f"[b, hkv, ctx, d] k/v, got {q.shape}/{k.shape}")
    b, h, t, d = q.shape
    hkv, ctx = k.shape[1], k.shape[2]
    if h % hkv:
        raise ValueError(f"query heads {h} not a multiple of kv heads "
                         f"{hkv}")
    if k.shape != v.shape:
        raise ValueError(f"k {k.shape} and v {v.shape} must match")
    if q_positions.shape != (b, t):
        raise ValueError(f"q_positions must be [b, t]={b, t}, got "
                         f"{q_positions.shape}")
    scale = d ** -0.5 if scale is None else scale
    bk = _fit_block(block_k, ctx)
    if interpret is None:
        if jax.default_backend() != "tpu":
            return _decode_xla(q, k, v, q_positions, scale, bk or ctx)
        interpret = False
    if not bk or t % 8 or d % 8 \
            or not _resident_fits(ctx, d, k.dtype):
        # Off-tile shapes / oversized caches leave the kernel path; the
        # fallback is the same math (and bit-identical where both run).
        _warn_fallback(
            f"flash_decode shapes t={t} d={d} ctx={ctx} off the kernel "
            f"tiles (or cache exceeds the VMEM budget)")
        return _decode_xla(q, k, v, q_positions, scale, bk or ctx)
    return _decode_pallas(q, k, v, q_positions, scale, bk, interpret)


def flash_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                            mesh, causal: bool = True,
                            scale: Optional[float] = None,
                            block_q: int = 256, block_k: int = 256,
                            model_axis: str = "model",
                            interpret: Optional[bool] = None) -> jax.Array:
    """Global-array entry point: shard_map the flash kernel over the mesh —
    batch over the data axes, heads over the tensor-parallel axis, sequence
    unsharded (intra-chip fusion is this kernel's job; a sharded sequence
    axis is :func:`tony_tpu.parallel.ring_attention_sharded`'s).

    GSPMD cannot partition a custom pallas call from sharding annotations
    alone — an unmapped kernel inside a tp>1 jit gets its operands
    all-gathered per device, defeating tensor parallelism — so models must
    route through this wrapper whenever a mesh is active.
    """
    from jax.sharding import PartitionSpec as P

    b, h = q.shape[0], q.shape[1]
    from tony_tpu.parallel.overlap import sync_axes  # call-time: no cycle

    dp_axes = sync_axes(mesh)
    dp_size = 1
    for a in dp_axes:
        dp_size *= mesh.shape[a]
    tp = model_axis if model_axis in mesh.axis_names else None
    tp_size = mesh.shape[tp] if tp else 1
    if b % dp_size or h % tp_size or k.shape[1] % tp_size:
        # shard_map needs exact divisibility (GQA: kv heads shard over the
        # same tp axis, so they must divide too); rather than hard-fail a
        # config the plain GSPMD path would run (slowly), fall back.
        _warn_fallback(
            f"batch {b} % dp {dp_size} or heads {h}/kv {k.shape[1]} % tp "
            f"{tp_size} != 0; flash kernel will run unmapped under GSPMD")
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k,
                               interpret=interpret)
    spec = P(dp_axes or None, tp, None, None)
    fn = functools.partial(flash_attention, causal=causal, scale=scale,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret)
    from tony_tpu.compat import shard_map as _shard_map
    return _shard_map(fn, mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)(q, k, v)
