"""Flash attention: fused online-softmax attention as a pallas TPU kernel.

The score matrix never leaves VMEM: each (batch·head, q-block) grid cell
streams K/V blocks through the online-softmax recurrence (running max m,
normalizer l, accumulator acc — same math as
:mod:`tony_tpu.parallel.ring_attention`, which runs the recurrence *across
chips* while this kernel runs it *within* one), so HBM traffic is O(T·D)
instead of O(T²) and the matmuls hit the MXU in bf16/f32 with f32
accumulation. Causal runs skip entire k-blocks above the diagonal — the
dominant win for long sequences.

Public entry :func:`flash_attention` dispatches: pallas kernel on TPU (or
``interpret=True`` for CPU tests), pure-JAX :func:`reference_attention`
elsewhere; the backward pass is the reference VJP under ``jax.checkpoint``
semantics (recompute, no saved T×T residuals).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True,
                        scale: Optional[float] = None) -> jax.Array:
    """Plain attention over [B, H, T, D], f32 softmax accumulation."""
    d = q.shape[-1]
    scale = d ** -0.5 if scale is None else scale
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t_q, t_k = q.shape[2], k.shape[2]
        mask = (jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
                >= jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float):
    """One grid cell: q-block [Bq, D] against the full K/V [T, D] in VMEM,
    streamed in block_k chunks through the online-softmax recurrence."""
    bq, d = q_ref.shape
    t = k_ref.shape[0]
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    a0 = jnp.zeros((bq, d), jnp.float32)

    if causal:
        # Only k-blocks touching or below the diagonal contribute.
        num_kb = pl.cdiv((qi + 1) * bq, block_k)
    else:
        num_kb = pl.cdiv(t, block_k)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [Bq, Bk]
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, a0))
    o_ref[:] = (acc / jnp.where(l > 0, l, 1.0)).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    b, h, t, d = q.shape
    tk = k.shape[2]
    grid = (b * h, pl.cdiv(t, block_q))
    qr = q.reshape(b * h, t, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)
    kernel = functools.partial(_flash_kernel, block_k=block_k,
                               causal=causal, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * t * tk * d // (2 if causal else 1),
            bytes_accessed=(qr.size + kr.size + vr.size) * q.dtype.itemsize,
            transcendentals=b * h * t * tk),
    )(qr, kr, vr)
    return out.reshape(b, h, t, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, residuals, g):
    # Recompute-based backward via the reference VJP: no T×T residuals were
    # saved by the forward (flash's whole point); the reference recompute is
    # one fused XLA graph. A dedicated pallas backward kernel can slot in
    # here without touching callers.
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: reference_attention(q_, k_, v_, causal, scale),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Fused attention over ``[batch, heads, seq, head_dim]``.

    Dispatch: the pallas kernel on TPU backends (or when ``interpret=True``
    forces the pallas interpreter — how CPU tests cover the kernel), the
    pure-JAX reference otherwise. Sequence length must divide by the block
    sizes on the kernel path; callers pad or fall back.
    """
    d = q.shape[-1]
    scale = d ** -0.5 if scale is None else scale
    t, tk = q.shape[2], k.shape[2]
    if interpret is None:
        on_tpu = jax.default_backend() == "tpu"
        if not on_tpu:
            return reference_attention(q, k, v, causal, scale)
        interpret = False
    block_q = min(block_q, t)
    block_k = min(block_k, tk)
    if t % block_q or tk % block_k:
        return reference_attention(q, k, v, causal, scale)
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)
