"""Quantized compute lane: int8 matmuls with f32 accumulation/rescale.

Int8 on the MXU doubles peak throughput over bf16 (v5e: 197 → 394 TOPS)
and halves every weight byte a collective ships — the one step-time lever
the kernel-level MFU push still had open after PR 7. Following
TF-Replicator's lesson (arXiv:1902.00465) the framework owns the whole
precision lane — scales, dtype policy, checkpoint semantics, static
verification — instead of leaving each user to rebuild it badly:

* :func:`quant_dot` / :func:`quant_dot_general` — symmetric int8
  quantization (per-tensor activations, per-channel or per-tensor
  weights) feeding an int8×int8→int32 matmul with an f32 rescale. Two
  execution paths share ONE rescale expression (:func:`_rescale`) and an
  exact integer accumulation, so they are bit-identical by construction:
  a pallas TPU kernel (``interpret=True`` is how CPU tests cover it, like
  ``ops/attention.py`` / ``ops/fused_optim.py``) and a pure-XLA
  ``lax.dot_general(preferred_element_type=int32)`` fallback. Gradients
  are straight-through (custom_vjp): the backward matmuls run in f32 on
  the dequantized operands — standard QAT semantics.
* :class:`QuantDense` — the drop-in ``nn.Dense`` twin the model lanes
  use (``models/transformer.py`` ``quant=`` projections, the mnist MLP's
  ``quant=True``): dynamic (current-tensor) scales, kernel logical
  partitioning preserved, param tree paths identical to ``nn.Dense``.
* Quantize-on-gather — the ZeRO-3 forward param gathers
  (:class:`tony_tpu.parallel.sched.GatherPlan`) optionally ship int8
  bytes: each even scatter bucket's local shard chunk is quantized with
  a bucket scale shared by every shard, gathered as int8 (4× fewer bytes
  than f32), and dequantized on arrival. Because the scale is shared,
  quantize∘gather ≡ gather∘quantize BIT-exact — packing int8 adds no
  error beyond quantization itself. Scales come from **delayed scaling**:
  a per-bucket amax history (:class:`QuantConfig.window` entries) updated
  inside the accum region like PR 7's opt slots — the region measures the
  current bucket amax (local max + ``pmax`` over fsdp), rolls it into the
  history, and NEXT step's scale is ``max(history) / 127``. The history
  rides :class:`QuantTrainState` and commits/restores through the PR 3
  manifest via a ``register_portable_codec`` entry whose portable form is
  per-LEAF (topology-independent — an fsdp=4 history restores onto fsdp=2
  re-bucketed, conservative max per bucket).

The whole lane is loss-pin gated (``tests/test_quant.py``): quantized
mnist-mlp / tiny-transformer training curves must track bf16 within the
committed tolerance, and the pallas kernel must match the XLA fallback
bit-exactly.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from flax.training.train_state import TrainState
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tony_tpu._trace import trace_record

# Trace-time side channel into the profiler registry (shared shim
# contract: lazy import, swallow-all, log-once — see tony_tpu._trace).
_record = functools.partial(trace_record, "quant")

# Symmetric int8: values in [-127, 127] (the -128 code is unused so the
# range is symmetric and negation is exact).
QMAX = 127.0

# Scales divide; an all-zero tensor must quantize to zeros, not NaNs.
AMAX_FLOOR = 1e-12


# ---------------------------------------------------------------------------
# Quantization math (one definition; every lane — kernel, fallback,
# gather — goes through these, so the numerics story has one source)
# ---------------------------------------------------------------------------

def scale_of(amax: jax.Array) -> jax.Array:
    """Symmetric scale from an amax statistic (elementwise over per-
    channel vectors): ``max(amax, floor) / 127``."""
    return jnp.maximum(jnp.asarray(amax, jnp.float32), AMAX_FLOOR) / QMAX


def quantize(x: jax.Array, scale: jax.Array) -> jax.Array:
    """Symmetric int8 quantization: ``clip(round(x / scale), ±127)``.
    ``scale`` broadcasts (scalar = per-tensor, trailing vector = per-
    channel). Round-to-nearest-even (``jnp.round``), everywhere."""
    return jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                    -QMAX, QMAX).astype(jnp.int8)


def dequantize(q: jax.Array, scale: jax.Array,
               dtype: Any = jnp.float32) -> jax.Array:
    return (q.astype(jnp.float32) * scale).astype(dtype)


def _rescale(acc: jax.Array, sx: jax.Array, sw: jax.Array) -> jax.Array:
    """THE f32 rescale of an int32 accumulator, shared VERBATIM by the
    pallas kernel body and the XLA fallback — with the integer matmul
    exact by construction, this one expression is why the two paths are
    bit-identical. ``sx`` is the scalar lhs scale, ``sw`` the [N] rhs
    scale vector (per-tensor rhs broadcasts the scalar into it)."""
    return acc.astype(jnp.float32) * (sx * sw)


def _resolve_impl(impl: Optional[str], interpret: bool) -> str:
    """Impl-dispatch policy, same as ops/attention.py / ops/fused_optim:
    explicit wins; else pallas on TPU or under the interpreter, the XLA
    fallback elsewhere."""
    if impl is not None:
        return impl
    return "pallas" if (interpret
                        or jax.default_backend() == "tpu") else "xla"


def _round_up(n: int, m: int) -> int:
    return n + ((-n) % m)


# ---------------------------------------------------------------------------
# The int8 matmul core: int8×int8 → int32 accumulate → f32 rescale
# ---------------------------------------------------------------------------

def _dot_kernel(sx_ref, x_ref, w_ref, sw_ref, o_ref):
    """One (bm, bn) output tile: whole-K int8 dot on the MXU with an
    int32 accumulator (exact — integer addition is associative, so the
    grid layout cannot perturb numerics), rescaled through the shared
    :func:`_rescale`."""
    acc = jax.lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    o_ref[:] = _rescale(acc, sx_ref[0], sw_ref[0])


# Output-tile targets: int8 operand tiles are (32, 128); 256×256 keeps
# the x/w/out VMEM blocks of one grid step under ~0.5 MiB combined.
_BM, _BN = 256, 256


def _int8_matmul(xq: jax.Array, wq: jax.Array, sx: jax.Array,
                 sw: jax.Array, *, impl: Optional[str],
                 interpret: bool) -> jax.Array:
    """``[M, K] int8 @ [K, N] int8 → [M, N] f32`` with f32 rescale —
    the dispatch point of the two bit-identical paths. ``sw`` is the
    [N] per-channel scale vector."""
    impl = _resolve_impl(impl, interpret)
    if impl == "xla":
        acc = jax.lax.dot_general(
            xq, wq, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        return _rescale(acc, sx, sw)
    if impl != "pallas":
        raise ValueError(f"unknown impl {impl!r} (pallas|xla)")
    m, k = xq.shape
    n = wq.shape[1]
    # int8 tiles are (32, 128): sublane dims pad to 32, lane dims to 128.
    # Zero pads are inert through an integer dot; padded output rows/cols
    # are sliced back off.
    bm = min(_BM, _round_up(m, 32))
    bn = min(_BN, _round_up(n, 128))
    mp, np_, kp = _round_up(m, bm), _round_up(n, bn), _round_up(k, 128)
    xq = jnp.pad(xq, ((0, mp - m), (0, kp - k)))
    wq = jnp.pad(wq, ((0, kp - k), (0, np_ - n)))
    sw2 = jnp.pad(sw, (0, np_ - n)).reshape(1, np_)
    out = pl.pallas_call(
        _dot_kernel,
        grid=(mp // bm, np_ // bn),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((bm, kp), lambda i, j: (i, 0)),
            pl.BlockSpec((kp, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * m * k * n,
            bytes_accessed=m * k + k * n + 4 * m * n + 4 * n,
            transcendentals=0),
    )(sx.reshape(1), xq, wq, sw2)
    return out[:m, :n]


def _qdot_impl(x: jax.Array, w: jax.Array, per_channel: bool,
               impl: Optional[str], interpret: bool):
    """Quantize + matmul, shared by the primal and fwd rules. Returns
    ``(y, (xq, sx, wq, sw))`` — the int8 residuals are what the STE
    backward dequantizes (4× smaller than f32 residuals)."""
    k = x.shape[-1]
    n = w.shape[1]
    x2 = x.reshape(-1, k)
    sx = scale_of(jnp.max(jnp.abs(x2.astype(jnp.float32))))
    if per_channel:
        aw = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)     # [N]
    else:
        aw = jnp.max(jnp.abs(w.astype(jnp.float32)))
    sw = jnp.broadcast_to(scale_of(aw), (n,))
    xq = quantize(x2, sx)
    wq = quantize(w, sw)
    y = _int8_matmul(xq, wq, sx, sw, impl=impl, interpret=interpret)
    return y.reshape(x.shape[:-1] + (n,)), (xq, sx, wq, sw)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _qdot(x, w, per_channel, impl, interpret):
    return _qdot_impl(x, w, per_channel, impl, interpret)[0]


def _qdot_fwd(x, w, per_channel, impl, interpret):
    y, res = _qdot_impl(x, w, per_channel, impl, interpret)
    # Dtype sentinels: residuals must be jax types, and the cotangents
    # must come back in the PRIMAL dtypes (x may be bf16 while y/g are
    # f32 — the rescale owns the output precision).
    return y, (res, jnp.zeros((), x.dtype), jnp.zeros((), w.dtype))


def _qdot_bwd(per_channel, impl, interpret, residuals, g):
    # Straight-through estimator: quantize∘dequantize ≈ identity for the
    # gradient, so the backward is the plain matmul transpose pair over
    # the DEQUANTIZED (fake-quant) operands, run in f32 — standard QAT.
    # (The int8 residuals are 4× smaller than stashing the f32 primals.)
    (xq, sx, wq, sw), xsent, wsent = residuals
    g2 = g.reshape(-1, g.shape[-1]).astype(jnp.float32)
    xshape = g.shape[:-1] + (wq.shape[0],)
    dx = (g2 @ dequantize(wq, sw).T).reshape(xshape).astype(xsent.dtype)
    dw = (dequantize(xq, sx).T @ g2).astype(wsent.dtype)
    return dx, dw


_qdot.defvjp(_qdot_fwd, _qdot_bwd)


def quant_dot(x: jax.Array, w: jax.Array, *, per_channel: bool = True,
              impl: Optional[str] = None, interpret: bool = False,
              tag: Optional[str] = None) -> jax.Array:
    """Quantized ``x @ w``: symmetric int8 (per-tensor ``x``, per-channel
    ``w`` by default), int8×int8→int32 matmul, f32 rescale, straight-
    through gradients. ``x`` is ``[..., K]``, ``w`` is ``[K, N]``; the
    result is f32 (cast at the call site — the f32 rescale IS the
    accumulation story, callers choose the storage dtype)."""
    if w.ndim != 2:
        raise ValueError(f"quant_dot expects a rank-2 rhs [K, N], got "
                         f"shape {w.shape}")
    if x.shape[-1] != w.shape[0]:
        raise ValueError(f"contraction mismatch: x[..., {x.shape[-1]}] "
                         f"@ w[{w.shape[0]}, ...]")
    m = int(np.prod(x.shape[:-1], dtype=np.int64))
    _record(tag or "dot", kind="dot", m=m, k=x.shape[-1], n=w.shape[1],
            impl=_resolve_impl(impl, interpret), per_channel=per_channel,
            int8_bytes=m * x.shape[-1] + x.shape[-1] * w.shape[1],
            bf16_bytes=2 * (m * x.shape[-1] + x.shape[-1] * w.shape[1]))
    return _qdot(x, w, per_channel, impl, interpret)


def quant_dot_general(lhs: jax.Array, rhs: jax.Array,
                      dimension_numbers: Any, **kw) -> jax.Array:
    """``lax.dot_general``-shaped entry over the quantized core: one
    contracting dim per side, no batch dims (the projection shapes the
    model lanes use). Anything else raises — the lane is explicit about
    what it owns."""
    (lc, rc), (lb, rb) = dimension_numbers
    if lb or rb or len(lc) != 1 or len(rc) != 1:
        raise NotImplementedError(
            "quant_dot_general supports a single contracting dim per "
            f"side and no batch dims, got {dimension_numbers}")
    lhs_t = jnp.moveaxis(lhs, lc[0], -1)
    rhs_t = jnp.moveaxis(rhs, rc[0], 0)
    rest = rhs_t.shape[1:]
    y = quant_dot(lhs_t, rhs_t.reshape(rhs_t.shape[0], -1), **kw)
    return y.reshape(lhs_t.shape[:-1] + rest)


class QuantDense(nn.Module):
    """``nn.Dense`` twin on the quantized lane: identical param tree
    paths (``kernel``/``bias``), kernel logical partitioning via
    ``kernel_init``, compute through :func:`quant_dot` with dynamic
    (current-tensor) scales. Embeddings and norms stay off this lane by
    policy — only matmul projections quantize."""

    features: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    use_bias: bool = False
    per_channel: bool = True
    impl: Optional[str] = None
    interpret: bool = False
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", self.kernel_init,
                            (x.shape[-1], self.features), self.param_dtype)
        y = quant_dot(x, kernel, per_channel=self.per_channel,
                      impl=self.impl, interpret=self.interpret,
                      tag=f"dense.{self.name}")
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (self.features,),
                              self.param_dtype)
            y = y + bias
        return y.astype(self.dtype)


# ---------------------------------------------------------------------------
# Quantize-on-gather: delayed scaling over the GatherPlan buckets
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class QuantConfig:
    """The quantized-gather lane's knobs. ``window`` is the delayed-
    scaling amax-history length (scales react within ``window`` steps of
    a weight-magnitude shift; longer = smoother). ``bucket_bytes`` names
    the bucket plan geometry the per-bucket amax state was built for —
    it must agree with the accum step's plan (validated, like the
    FusedOptimizer's), and the ckpt codec re-derives the plan from it."""

    window: int = 8
    bucket_bytes: int = 4 << 20        # overlap.DEFAULT_BUCKET_BYTES

    def __post_init__(self):
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")


class QuantTrainState(TrainState):
    """TrainState + the quantized-gather lane's state: ``quant_state`` is
    ``{"amax": [per-gather-bucket f32 [window] history]}`` (replicated —
    scales must be identical on every shard for the int8 gather to be
    exact), ``qconfig`` the static :class:`QuantConfig`. Master params
    and the ZeRO-3 scatter buckets are untouched — quantization lives
    only on the forward-gather wire."""

    qconfig: Any = struct.field(pytree_node=False, default=None)
    quant_state: Any = None


def push_amax(hist: jax.Array, amax: jax.Array) -> jax.Array:
    """Roll one fresh amax into a [window] history (oldest falls out)."""
    return jnp.roll(hist, -1).at[-1].set(amax.astype(jnp.float32))


def hist_scale(hist: jax.Array) -> jax.Array:
    """Delayed scale from a history: ``max(hist) / 127``."""
    return scale_of(jnp.max(hist))


def bucket_amax(leaves: Sequence[jax.Array]) -> jax.Array:
    """Current amax of one bucket = max over its member leaves' |max|
    (identical to the packed buffer's amax — max commutes with concat,
    so no buffer is ever built for the statistic)."""
    return functools.reduce(
        jnp.maximum,
        [jnp.max(jnp.abs(l.astype(jnp.float32))) for l in leaves])


def is_quant_state(state: Any) -> bool:
    """A TrainState riding the quantized-gather lane."""
    return getattr(state, "quant_state", None) is not None \
        and getattr(state, "qconfig", None) is not None


def check_quant_amax(gplan: Any, amax: Sequence[jax.Array]) -> None:
    """The amax state must match THIS gather plan's bucket geometry —
    a mismatch means it was built for a different bucket_bytes or fsdp
    topology (rebuild via :func:`with_gather_quant` or elastic-restore
    through the portable leaf-major form). The accum engine calls this
    before every quantized trace."""
    if len(amax) != gplan.n_gather_buckets:
        raise ValueError(
            f"quant_amax carries {len(amax)} histories but the gather "
            f"plan has {gplan.n_gather_buckets} buckets — the state was "
            f"built for a different bucket_bytes or fsdp topology; "
            f"rebuild it (with_gather_quant) or restore through the "
            f"portable leaf-major form")
    for k, h in enumerate(amax):
        shape = tuple(getattr(h, "shape", ()))
        if len(shape) != 1 or shape[0] < 1 or (
                k and shape != tuple(amax[0].shape)):
            raise ValueError(
                f"amax history {k} has shape {shape} — every history "
                f"must be one non-empty [window] f32 vector (bucket 0's "
                f"is {tuple(amax[0].shape)})")


def _plans_of(params: Any, mesh: Optional[Mesh], bucket_bytes: int):
    """(plan, gplan) for the quantized-gather lane, the same derivation
    the accum step uses (overlap.step_plans) — state init, the stepper,
    and the ckpt codec must all see identical bucket geometry."""
    from tony_tpu.parallel import overlap

    if mesh is None:
        raise ValueError(
            "quantize-on-gather needs a ZeRO-3 (fsdp-sharded) layout on "
            "a mesh — no mesh found on the params")
    specs = overlap.fsdp_param_specs(params, mesh)
    if specs is None:
        raise ValueError(
            "quantize-on-gather needs fsdp-sharded params (the lane "
            "quantizes the forward param gathers; a replicated layout "
            "has none)")
    return overlap.step_plans(params, mesh, bucket_bytes=bucket_bytes,
                              param_specs=specs)


def with_gather_quant(state: Any, mesh: Mesh, *,
                      window: int = 8,
                      bucket_bytes: Optional[int] = None
                      ) -> QuantTrainState:
    """Attach the quantized-gather lane to a TrainState: derive the
    gather plan from the params' committed shardings and seed every
    bucket's [window] amax history from the CURRENT param magnitudes (so
    step 1's delayed scale is already calibrated). ``bucket_bytes``
    defaults from a FusedOptimizer tx when present (the tx's plan sized
    everything else bucket-shaped)."""
    if bucket_bytes is None:
        bucket_bytes = getattr(state.tx, "bucket_bytes", None)
        if bucket_bytes is None:
            from tony_tpu.parallel.overlap import DEFAULT_BUCKET_BYTES
            bucket_bytes = DEFAULT_BUCKET_BYTES
    qcfg = QuantConfig(window=window, bucket_bytes=bucket_bytes)
    plan, gplan = _plans_of(state.params, mesh, bucket_bytes)
    leaves = jax.tree.leaves(state.params)
    rep = NamedSharding(mesh, P())
    amax = []
    for b in gplan.gather_buckets:
        m = bucket_amax([leaves[i] for i in plan.buckets[b]])
        amax.append(jax.device_put(jnp.full((window,), m, jnp.float32),
                                   rep))
    _record("attach", n_buckets=gplan.n_gather_buckets, window=window,
            bucket_bytes=bucket_bytes,
            raw_nbytes=list(gplan.gather_nbytes),
            int8_nbytes=[plan.bucket_numel[b]
                         for b in gplan.gather_buckets])
    return QuantTrainState(
        step=state.step, apply_fn=state.apply_fn, params=state.params,
        tx=state.tx, opt_state=state.opt_state, qconfig=qcfg,
        quant_state={"amax": amax})


def gather_roundtrip_exact(params: Any, mesh: Mesh,
                           bucket_bytes: int) -> bool:
    """The quantize-on-gather bit-exactness pin, as a callable check the
    tests and the bench leg share: gathering int8 then dequantizing must
    equal quantize∘dequantize of the UNQUANTIZED gather, leaf for leaf,
    bit for bit (shared scales commute with the collective)."""
    from tony_tpu import compat
    from tony_tpu.parallel import overlap

    specs = overlap.fsdp_param_specs(params, mesh)
    plan, gplan = overlap.step_plans(params, mesh,
                                     bucket_bytes=bucket_bytes,
                                     param_specs=specs)
    p_specs, _ = overlap.region_param_specs(plan, specs)
    from tony_tpu.parallel import FSDP

    def spmd(p):
        lv = jax.tree.leaves(p)
        # The shared per-bucket scale, computed exactly like the accum
        # engine does: local bucket amax, pmax over fsdp — identical on
        # every shard, which is WHY quantize commutes with the gather.
        scales = [scale_of(jax.lax.pmax(
            bucket_amax([lv[i] for i in plan.buckets[b]]), FSDP))
            for b in gplan.gather_buckets]
        leaf_scale: Dict[int, jax.Array] = {}
        for k, b in enumerate(gplan.gather_buckets):
            for i in plan.buckets[b]:
                leaf_scale[i] = scales[k]
        q_full = gplan.gather(list(lv), scales=scales)
        full = gplan.gather(list(lv))
        ref = [dequantize(quantize(full[i], leaf_scale[i]),
                          leaf_scale[i], full[i].dtype)
               if i in leaf_scale else full[i]
               for i in range(len(full))]
        ok = jnp.bool_(True)
        for a, b in zip(q_full, ref):
            ok = jnp.logical_and(ok, jnp.all(a == b))
        return ok

    flat_specs = jax.tree.leaves(p_specs,
                                 is_leaf=lambda x: isinstance(x, P))
    out = compat.shard_map(
        lambda *lv: spmd(jax.tree.unflatten(plan.treedef, list(lv))),
        mesh, in_specs=tuple(flat_specs), out_specs=P())(
            *jax.tree.leaves(params))
    return bool(jax.device_get(out))


# ---------------------------------------------------------------------------
# Ckpt portability codec: per-bucket amax ⇄ per-leaf amax
# ---------------------------------------------------------------------------

def _mesh_of(params: Any) -> Optional[Mesh]:
    for leaf in jax.tree.leaves(params):
        mesh = getattr(getattr(leaf, "sharding", None), "mesh", None)
        if mesh is not None and getattr(mesh, "axis_names", None):
            return mesh
    return None


def amax_to_leaf_major(plan: Any, gplan: Any,
                       amax: Sequence[jax.Array]) -> Any:
    """Per-bucket histories → a param-shaped pytree of [window] f32
    arrays (host numpy): every member leaf carries its bucket's history,
    non-gathered leaves carry zeros. Leaf paths are topology-independent
    — the portable form the manifest records."""
    window = int(amax[0].shape[0]) if amax else 1
    leaves: List[Any] = [np.zeros((window,), np.float32)
                         for _ in plan.shapes]
    for k, b in enumerate(gplan.gather_buckets):
        h = np.asarray(jax.device_get(amax[k]), np.float32)
        for i in plan.buckets[b]:
            leaves[i] = h
    return jax.tree.unflatten(plan.treedef, leaves)


def leaf_major_to_amax(plan: Any, gplan: Any, tree: Any,
                       mesh: Optional[Mesh]) -> List[jax.Array]:
    """Inverse of :func:`amax_to_leaf_major` onto THIS plan's buckets:
    bucket history = elementwise max over member leaves' histories (the
    conservative merge when the bucket partition changed across an
    elastic restore — a too-large scale quantizes coarser for ``window``
    steps, never clips). A bucket whose members ALL carry zero histories
    (gatherable only on this topology) merges to zeros — the decode path
    re-seeds those from the live params, because a floored scale would
    clip, not coarsen."""
    leaves = [np.asarray(jax.device_get(l), np.float32)
              for l in jax.tree.leaves(tree)]
    out: List[jax.Array] = []
    rep = NamedSharding(mesh, P()) if mesh is not None else None
    for b in gplan.gather_buckets:
        h = functools.reduce(np.maximum,
                             [leaves[i] for i in plan.buckets[b]])
        buf = jnp.asarray(h, jnp.float32)
        if rep is not None:
            buf = jax.device_put(buf, rep)
        out.append(buf)
    return out


def encode_state(state: Any) -> Any:
    """Ckpt codec, encode half: per-bucket amax → portable per-leaf form
    (and the fused optimizer's slots through ITS codec — the quant codec
    composes so a fused+quant state round-trips whole)."""
    from tony_tpu.ops import fused_optim

    if not is_quant_state(state):
        return fused_optim.encode_state(state)
    inner = fused_optim.encode_state(state)
    if "amax" not in state.quant_state:
        return inner
    plan, gplan = _plans_of(state.params, _mesh_of(state.params),
                            state.qconfig.bucket_bytes)
    return inner.replace(quant_state={
        "amax_leaf": amax_to_leaf_major(plan, gplan,
                                        state.quant_state["amax"])})


def decode_state(state: Any, mesh: Optional[Mesh] = None) -> Any:
    """Ckpt codec, decode half: portable per-leaf amax → per-bucket
    histories re-planned for THE CURRENT topology."""
    from tony_tpu.ops import fused_optim

    if not is_quant_state(state):
        return fused_optim.decode_state(state, mesh)
    inner = fused_optim.decode_state(state, mesh)
    if "amax_leaf" not in state.quant_state:
        return inner
    if mesh is None:
        mesh = _mesh_of(state.params)
    plan, gplan = _plans_of(state.params, mesh,
                            state.qconfig.bucket_bytes)
    # Restored scalars (step, an optax count, ...) may come back
    # committed to a single device when the restore template's own
    # scalar was single-device; the step jit then refuses the mixed
    # device sets. Re-place every opt_state/step SCALAR replicated —
    # the same fix the fused codec applies to its count, generalized so
    # a quant state restores jit-consistent under any tx.
    rep = NamedSharding(mesh, P())

    def _respread(leaf):
        if getattr(leaf, "ndim", None) == 0:
            return jax.device_put(jnp.asarray(jax.device_get(leaf)), rep)
        return leaf

    step = inner.step
    if getattr(step, "ndim", None) == 0:
        step = jax.device_put(jnp.asarray(jax.device_get(step)), rep)
    amax = leaf_major_to_amax(plan, gplan,
                              state.quant_state["amax_leaf"], mesh)
    # A bucket that became gatherable only on THIS topology (e.g. a leaf
    # that was uneven at the saving fsdp degree and is even now) merges
    # an all-zero portable history — and a zero history floors the scale
    # at AMAX_FLOOR/127, which would CLIP that bucket's params to ~0 on
    # the first step. Re-seed such buckets from the current param
    # magnitudes, exactly like with_gather_quant does at attach time.
    leaves = jax.tree.leaves(inner.params)
    window = state.qconfig.window
    for k, b in enumerate(gplan.gather_buckets):
        if float(jnp.max(amax[k])) == 0.0:
            m = bucket_amax([leaves[i] for i in plan.buckets[b]])
            amax[k] = jax.device_put(
                jnp.full((window,), m, jnp.float32), rep)
    return inner.replace(
        step=step,
        opt_state=jax.tree.map(_respread, inner.opt_state),
        quant_state={"amax": amax})


def _register_codec() -> None:
    from tony_tpu import ckpt

    # Prepend: a fused+quant state matches the fused codec's predicate
    # too, but only this codec handles BOTH planes (it delegates the
    # slots to fused_optim's) — first match wins in the registry.
    ckpt.register_portable_codec(
        "quant_gather", is_quant_state, encode_state, decode_state,
        prepend=True)


_register_codec()
