"""Hot-path TPU ops: pallas kernels + their portable references.

The reference framework has NO native compute (SURVEY.md §2: TonY is ~100%
JVM orchestration; kernels live in the frameworks it launches). This package
is where the TPU rebuild's compute plane keeps its hand-written kernels —
only the ops where beating XLA's fusion is realistic (attention; XLA already
fuses elementwise chains and layernorms well). Every op ships with a pure-JAX
reference implementation used for CPU tests and as the autodiff backward.
"""

from tony_tpu.ops.attention import (
    flash_attention, flash_attention_packed, flash_attention_sharded,
    flash_decode, reference_attention)
from tony_tpu.ops.fused_optim import (FusedOptimizer, fused_bucket_update,
                                      fused_update_step)
from tony_tpu.ops.quant import (QuantConfig, QuantDense, QuantTrainState,
                                quant_dot, quant_dot_general,
                                with_gather_quant)

__all__ = ["flash_attention", "flash_attention_packed",
           "flash_attention_sharded", "flash_decode",
           "reference_attention",
           "FusedOptimizer", "fused_bucket_update", "fused_update_step",
           "QuantConfig", "QuantDense", "QuantTrainState", "quant_dot",
           "quant_dot_general", "with_gather_quant"]
