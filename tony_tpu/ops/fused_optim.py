"""Bucket-major fused optimizer plane: one update kernel per ZeRO-3 bucket.

After PR 5 the backward half of a ZeRO-3 step is bucket-major end to end:
``psum_scatter`` lands each microbatch's gradients as flat, shard-major,
per-dtype bucket buffers (:class:`tony_tpu.parallel.overlap.GradBuckets`).
The optimizer update then *threw that away* — it unpacked the buffers back
into the leaf pytree and ran optax's per-leaf op soup: hundreds of tiny
multiply/adds, dispatch-bound and re-fragmenting exactly the tensors the
planner spent a PR coalescing (Horovod's lesson, arXiv:1802.05799: bucket
wins are lost if any stage re-fragments; T3, arXiv:2401.16677, makes the
same fused-granularity argument for the compute side of a collective's
producer/consumer chain). This module keeps the step bucket-major through
the update:

* :func:`fused_bucket_update` — ONE kernel launch per bucket: a pallas TPU
  kernel (``interpret=True`` for CPU tests, like ``ops/attention.py``) or a
  bit-identical pure-XLA ``jnp`` fallback, applying AdamW / SGD-momentum /
  Adafactor-style updates elementwise over the concatenated 1-D buffers —
  grads, params, and moment slots all in the bucket layout. The per-element
  math is a handful of flops over 4R+3W f32 bytes: bytes-bound (see the
  ROOFLINE.md entry), so the win is launch-count and layout, not flops.
* :class:`FusedOptimizer` — the rule + hyperparameters + bucket plan
  policy. ``init_state`` builds **bucket-resident** optimizer state: per-
  bucket f32 moment buffers stored in the scatter layout (sharded
  ``P(fsdp)`` for scatter buckets), so the ZeRO-3 step performs
  reduce → update entirely in the shard domain. The AdamW and SGD-momentum
  rules replicate optax's op order exactly — pinned BIT-exact in f32
  against ``optax.adamw`` / ``optax.sgd`` (bf16 params carry a documented
  tolerance: optax keeps bf16 moments, this plane keeps f32 slots). The
  ``adafactor`` rule is Adafactor-STYLE — second-moment-only, elementwise,
  non-factored (the factored row/col statistics need leaf geometry a flat
  bucket erases) — and is pinned against its own leaf-major reference.
* :func:`region_apply` (method) — the in-region core the accum engine
  calls (:func:`tony_tpu.parallel.overlap.microbatch_grads` with
  ``fused=``): bucket-major global grad norm (one fused reduction per
  buffer, ``psum`` over fsdp for scatter chunks), optional global-norm
  clipping, then the per-bucket update. Padded uneven-shard buckets stay
  inert in their pad rows: the pads are zero in grads (sums of the
  planner's zero padding), params (zero-padded at pack), and slots (init
  zero), and every rule maps (0, 0, 0) → (0, 0), weight decay included.
* leaf-major ⇄ bucket-major converters + a ckpt codec
  (:func:`encode_state` / :func:`decode_state`, registered with
  :mod:`tony_tpu.ckpt`): checkpoints carry the moments in the portable
  leaf-major form — leaf paths and shapes identical to the params — so
  existing manifests keep restoring and a fused state written on one
  fsdp/slice topology elastic-restores onto another, re-planned into that
  topology's buckets.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tony_tpu._trace import trace_record
from tony_tpu.parallel import FSDP
from tony_tpu.parallel.overlap import DEFAULT_BUCKET_BYTES, GradBuckets

# Trace-time side channel into the profiler registry (shared shim contract:
# lazy import, swallow-all, log-once — see tony_tpu._trace).
_record = functools.partial(trace_record, "update")

RULES: Tuple[str, ...] = ("adamw", "sgd", "adafactor")

# Moment slots per rule, in kernel-operand order.
_SLOTS: Dict[str, Tuple[str, ...]] = {
    "adamw": ("mu", "nu"),
    "sgd": ("trace",),
    "adafactor": ("nu",),
}

# Scalar operand layout (one tiny f32 vector per step, shared by every
# bucket's launch): [-lr, adam bias-correction 1, bias-correction 2, pad].
_N_SCAL = 4


def _rule_math(rule: str, g, p, slots, neg_lr, bc1, bc2, *, b1: float,
               b2: float, eps: float, weight_decay: float, momentum: float):
    """The per-element update, shared VERBATIM by the pallas kernel body
    and the XLA fallback (one math definition — the two paths are
    bit-identical by construction). ``g``/``p``/``slots`` are f32; the op
    order replicates optax exactly (``(1-b)*g + b*m``, bias-correct by
    division, ``sqrt(v̂)+eps``, decayed weights added to the update, scale
    by ``-lr`` last) so the f32 pin against optax is bit-exact."""
    if rule == "adamw":
        mu, nu = slots
        mu = (1 - b1) * g + b1 * mu
        nu = (1 - b2) * (g * g) + b2 * nu
        u = (mu / bc1) / (jnp.sqrt(nu / bc2) + eps)
        if weight_decay:
            u = u + weight_decay * p
        return p + neg_lr * u, (mu, nu)
    if rule == "sgd":
        (tr,) = slots
        tr = g + momentum * tr            # optax trace: g + decay * t
        u = tr
        if weight_decay:
            u = u + weight_decay * p
        return p + neg_lr * u, (tr,)
    if rule == "adafactor":
        # Adafactor-STYLE: second-moment-only, elementwise, no factoring
        # and no bias correction — deliberately free of any buffer-wide
        # statistic (an RMS clip over the buffer would count pad rows and
        # break uneven-shard inertness).
        (nu,) = slots
        nu = (1 - b2) * (g * g) + b2 * nu
        u = g / (jnp.sqrt(nu) + eps)
        if weight_decay:
            u = u + weight_decay * p
        return p + neg_lr * u, (nu,)
    raise ValueError(f"unknown fused optimizer rule {rule!r} "
                     f"(one of {RULES})")


def _update_kernel(nslots: int, rule: str, hyper: Dict[str, float]):
    """Kernel factory: ``(scal, g, p, *slots) -> (new_p, *new_slots)`` over
    one ``(block_rows, 128)`` tile. Scalars ride SMEM; everything else is a
    VMEM block of the padded-2D view of the 1-D bucket buffer."""

    def kernel(scal_ref, g_ref, p_ref, *refs):
        slot_refs = refs[:nslots]
        new_p_ref = refs[nslots]
        new_slot_refs = refs[nslots + 1:]
        neg_lr = scal_ref[0]
        bc1 = scal_ref[1]
        bc2 = scal_ref[2]
        g = g_ref[:].astype(jnp.float32)
        p = p_ref[:]
        p_new, new_slots = _rule_math(
            rule, g, p.astype(jnp.float32),
            tuple(r[:] for r in slot_refs), neg_lr, bc1, bc2, **hyper)
        new_p_ref[:] = p_new.astype(new_p_ref.dtype)
        for r, v in zip(new_slot_refs, new_slots):
            r[:] = v

    return kernel


def _round_up(n: int, m: int) -> int:
    return n + ((-n) % m)


def _resolve_impl(impl: Optional[str], interpret: bool) -> str:
    """THE impl-dispatch policy (one definition: the kernel entry and the
    profiler record must never disagree): explicit wins; else pallas on
    TPU or under the interpreter, the XLA fallback elsewhere."""
    if impl is not None:
        return impl
    return "pallas" if (interpret
                        or jax.default_backend() == "tpu") else "xla"


# Per-operand VMEM block: 1024 rows x 128 lanes x 4 B = 512 KiB; with the
# ~7 live operands of an adamw launch that is ~3.5 MiB — comfortable
# against the 16 MiB/core budget while big enough to amortize grid steps.
_BLOCK_ROWS = 1024


def fused_bucket_update(g: jax.Array, p: jax.Array,
                        slots: Sequence[jax.Array], scal: jax.Array, *,
                        rule: str, hyper: Dict[str, float],
                        impl: Optional[str] = None,
                        interpret: bool = False
                        ) -> Tuple[jax.Array, Tuple[jax.Array, ...]]:
    """ONE optimizer-update launch over one bucket's 1-D buffers.

    ``g``/``p`` are the bucket's gradient and parameter buffers (the
    bucket's storage dtype); ``slots`` are its f32 moment buffers (count
    and order per ``_SLOTS[rule]``); ``scal`` is the ``_N_SCAL``-vector
    from :meth:`FusedOptimizer.scalars`. Returns ``(new_p, new_slots)``
    with dtypes preserved.

    Dispatch mirrors ``ops/attention.py``: the pallas kernel on TPU (or
    under ``interpret=True`` — how CPU tests cover the kernel), the pure-
    XLA fallback elsewhere (``impl="xla"``); both run the SAME
    ``_rule_math`` and are bit-identical. The 1-D buffer is viewed as a
    zero-padded ``(rows, 128)`` f32-tile-legal 2-D array for the kernel;
    the edge pad is sliced back off (interior uneven-shard pads are the
    planner's and stay in place — zeros in, zeros out).
    """
    if rule not in RULES:
        raise ValueError(f"unknown fused optimizer rule {rule!r} "
                         f"(one of {RULES})")
    nslots = len(_SLOTS[rule])
    if len(slots) != nslots:
        raise ValueError(f"rule {rule!r} expects {nslots} slot buffer(s) "
                         f"({_SLOTS[rule]}), got {len(slots)}")
    impl = _resolve_impl(impl, interpret)
    if impl == "xla":
        p_new, new_slots = _rule_math(
            rule, g.astype(jnp.float32), p.astype(jnp.float32),
            tuple(slots), scal[0], scal[1], scal[2], **hyper)
        return p_new.astype(p.dtype), new_slots
    if impl != "pallas":
        raise ValueError(f"unknown impl {impl!r} (pallas|xla)")

    n = g.shape[0]
    rows = max(1, -(-n // 128))
    block_rows = min(_BLOCK_ROWS, _round_up(rows, 8))
    rows_p = _round_up(rows, block_rows)
    pad = rows_p * 128 - n

    def to2(x):
        if pad:
            x = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])
        return x.reshape(rows_p, 128)

    blk = pl.BlockSpec((block_rows, 128), lambda i: (i, 0))
    out_shapes = [jax.ShapeDtypeStruct((rows_p, 128), p.dtype)] + [
        jax.ShapeDtypeStruct((rows_p, 128), jnp.float32)] * nslots
    outs = pl.pallas_call(
        _update_kernel(nslots, rule, hyper),
        grid=(rows_p // block_rows,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [blk] * (2 + nslots),
        out_specs=tuple([blk] * (1 + nslots)),
        out_shape=tuple(out_shapes),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=12 * n,
            bytes_accessed=(g.size * g.dtype.itemsize
                            + 2 * p.size * p.dtype.itemsize
                            + 8 * nslots * n),
            transcendentals=n),
    )(scal, to2(g), to2(p), *[to2(s) for s in slots])
    p_new = outs[0].reshape(-1)[:n]
    new_slots = tuple(o.reshape(-1)[:n] for o in outs[1:])
    return p_new, new_slots


@dataclass(frozen=True)
class FusedOptimizer:
    """Rule + hyperparameters + bucket policy of the fused optimizer plane.

    Passed as the ``tx`` of a :class:`~flax.training.train_state.TrainState`
    (``train.create_train_state`` detects it and builds bucket-resident
    state); ``train.make_accum_train_step(update="fused_bucket")`` drives
    the in-region update. ``lr`` may be a python float or a callable
    ``count -> lr`` (schedule, resolved per step at trace time).

    AdamW and SGD-momentum replicate optax bit-exact in f32
    (``optax.adamw(lr, b1, b2, eps, weight_decay=...)`` with ``mask=None``;
    ``optax.sgd(lr, momentum)`` — for the exact sgd pin keep
    ``weight_decay=0``, optax's sgd has none). ``clip_norm`` applies
    global-norm clipping from the bucket-major norm before the update
    (optax's ``clip_by_global_norm`` formula; the norm itself differs from
    the per-leaf reduction only by fp reassociation).
    """

    rule: str = "adamw"
    lr: Union[float, Callable[[jax.Array], Any]] = 1e-3
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.0
    momentum: float = 0.9
    clip_norm: Optional[float] = None
    bucket_bytes: int = DEFAULT_BUCKET_BYTES
    impl: Optional[str] = None      # None = auto: pallas on TPU, xla else
    interpret: bool = False         # force the pallas interpreter (tests)

    def __post_init__(self):
        if self.rule not in RULES:
            raise ValueError(f"unknown fused optimizer rule {self.rule!r} "
                             f"(one of {RULES})")

    @property
    def slot_names(self) -> Tuple[str, ...]:
        return _SLOTS[self.rule]

    @property
    def hyper(self) -> Dict[str, float]:
        return {"b1": self.b1, "b2": self.b2, "eps": self.eps,
                "weight_decay": self.weight_decay,
                "momentum": self.momentum}

    def resolved_impl(self) -> str:
        return _resolve_impl(self.impl, self.interpret)

    def scalars(self, count: jax.Array) -> jax.Array:
        """The per-step scalar vector (one per step, shared by every
        bucket launch): ``[-lr, 1-b1^t, 1-b2^t, 0]``. The bias-correction
        expressions mirror optax's (python-float base ** int32 count) so
        the f32 pin stays bit-exact."""
        if self.rule == "adamw":
            bc1 = 1 - self.b1 ** count
            bc2 = 1 - self.b2 ** count
        else:
            bc1 = bc2 = jnp.float32(1.0)
        lr = self.lr(count) if callable(self.lr) else self.lr
        return jnp.stack([jnp.asarray(-lr, jnp.float32),
                          jnp.asarray(bc1, jnp.float32),
                          jnp.asarray(bc2, jnp.float32),
                          jnp.float32(0.0)])

    # -- planning / state ---------------------------------------------------

    def plan_for(self, params: Any, mesh: Optional[Mesh]) -> GradBuckets:
        """The deterministic bucket plan for THIS (params, topology): the
        same derivation everywhere (state init, train step, elastic
        restore), so bucket-resident buffers always line up."""
        from tony_tpu.parallel import overlap

        specs = overlap.fsdp_param_specs(params, mesh) \
            if mesh is not None else None
        if specs is None:
            return GradBuckets.plan(params, self.bucket_bytes)
        return GradBuckets.plan_sharded(
            params, specs, shard_size=mesh.shape[FSDP],
            bucket_bytes=self.bucket_bytes)

    def bucket_specs(self, plan: GradBuckets) -> List[P]:
        """Per-bucket shard_map/NamedSharding specs of the bucket-domain
        buffers: scatter buckets live in the scatter layout (``P(fsdp)``),
        the rest replicated."""
        return [P(FSDP) if plan._is_scatter(b) else P()
                for b in range(plan.n_buckets)]

    def init_state(self, params: Any, mesh: Optional[Mesh] = None,
                   plan: Optional[GradBuckets] = None) -> Dict[str, Any]:
        """Bucket-resident zero state: ``{"count": int32 0, "slots":
        {name: [per-bucket f32 buffer]}}`` with scatter buckets' buffers
        sharded ``P(fsdp)`` on ``mesh`` — the layout the in-region update
        consumes directly, no resharding on the step path."""
        plan = self.plan_for(params, mesh) if plan is None else plan
        specs = self.bucket_specs(plan)
        slots: Dict[str, List[jax.Array]] = {}
        for name in self.slot_names:
            bufs = []
            for b in range(plan.n_buckets):
                buf = jnp.zeros((plan.bucket_numel[b],), jnp.float32)
                if mesh is not None:
                    buf = jax.device_put(
                        buf, NamedSharding(mesh, specs[b]))
                bufs.append(buf)
            slots[name] = bufs
        count = jnp.zeros((), jnp.int32)
        if mesh is not None:
            count = jax.device_put(count, NamedSharding(mesh, P()))
        return {"count": count, "slots": slots}

    def check_slots(self, plan: GradBuckets, slots: Dict[str, Any]) -> None:
        names = tuple(slots)
        if set(names) != set(self.slot_names):
            raise ValueError(
                f"fused opt state carries slots {sorted(names)} but rule "
                f"{self.rule!r} needs {sorted(self.slot_names)}")
        for name in names:
            if len(slots[name]) != plan.n_buckets:
                raise ValueError(
                    f"fused opt state slot {name!r} has "
                    f"{len(slots[name])} bucket buffers but the plan has "
                    f"{plan.n_buckets} — the state was initialized for a "
                    f"different bucket_bytes or fsdp topology; rebuild it "
                    f"(create_train_state) or elastic-restore through the "
                    f"leaf-major portable form")

    # -- the in-region core -------------------------------------------------

    def local_pack(self, plan: GradBuckets, leaves: Sequence[Any], b: int,
                   f_idx, *, axis: str = FSDP, sharded: bool = True):
        """Region-LOCAL bucket packing: build bucket ``b``'s buffer from
        this device's view of the leaves — even scatter leaves are their
        local shard already, padded leaves are zero-padded and sliced to
        shard ``f_idx``, everything else concatenates whole. This is the
        only packing the fused plane ever does on sharded data: global
        ``pack()`` would route the concat through GSPMD (and the jax-0.4
        partitioner mis-reshards concatenated slice chunks on multi-axis
        meshes — measured, not hypothetical), while local packs are plain
        per-device data movement."""
        idxs = plan.buckets[b]
        if plan._is_scatter(b) and sharded and plan._is_padded(b):
            parts = []
            for i in idxs:
                d = plan.shard_dims[i]
                leaf = leaves[i]
                widths = [(0, plan._pad(i) if k == d else 0)
                          for k in range(len(plan.shapes[i]))]
                leaf = jnp.pad(leaf, widths)
                nrows = plan.padded_shape(i)[d] // plan.shard_size
                parts.append(jnp.ravel(jax.lax.dynamic_slice_in_dim(
                    leaf, f_idx * nrows, nrows, axis=d)))
        else:
            parts = [jnp.ravel(leaves[i]) for i in idxs]
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    def region_apply(self, plan: GradBuckets, param_leaves: Sequence[Any],
                     grad_bufs: Sequence[jax.Array], slots: Dict[str, Any],
                     scal: jax.Array, *, axis: str = FSDP,
                     sharded: Optional[bool] = None):
        """Bucket-major update core. Called INSIDE a manually-sharded
        region over ``axis`` when the plan has scatter buckets (the accum
        engine's region, or :func:`fused_update_step`'s wrapper); callable
        outside any region for shard-free plans.

        ``param_leaves`` are the region-local leaves (scatter leaves in
        shard shape, uneven/replicated leaves whole); ``grad_bufs`` the
        per-bucket gradient buffers in the same local layout the scan
        accumulators have (scatter chunk / full). Returns
        ``(new_param_leaves, new_slots, grad_norm)`` where the norm is the
        bucket-major global grad norm (one fused reduction per buffer,
        ``psum`` over ``axis`` for the disjoint scatter chunks) and the
        update saw ``clip_norm`` applied when configured.
        """
        self.check_slots(plan, slots)
        shard = plan.shard_size > 1
        if sharded is None:
            sharded = shard

        # Bucket-major global grad norm: one sum-of-squares per buffer.
        sq = jnp.float32(0.0)
        for b, gb in enumerate(grad_bufs):
            s = jnp.sum(jnp.square(gb.astype(jnp.float32)))
            if plan._is_scatter(b) and sharded:
                s = jax.lax.psum(s, axis)
            sq = sq + s
        gnorm = jnp.sqrt(sq)
        if self.clip_norm is not None:
            # optax.clip_by_global_norm's trim ratio, from the bucket norm.
            trim = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
            grad_bufs = [gb * trim.astype(gb.dtype) for gb in grad_bufs]

        new_leaves: List[Any] = list(param_leaves)
        new_slots: Dict[str, List[Any]] = {n: [None] * plan.n_buckets
                                           for n in self.slot_names}
        needs_f = sharded and any(
            plan._is_scatter(b) and plan._is_padded(b)
            for b in range(plan.n_buckets))
        f_idx = jax.lax.axis_index(axis) if needs_f else None
        for b, idxs in enumerate(plan.buckets):
            scatter = plan._is_scatter(b) and sharded
            padded = plan._is_padded(b)
            # Even scatter buckets: the local leaves ARE shard f, so the
            # local pack is pack()'s chunk f. Padded buckets: leaves
            # crossed the region replicated; local_pack zero-pads and
            # slices THIS device's shard so the buffer matches the grad
            # chunk's layout (pad rows zeros — inert through every rule).
            p_buf = self.local_pack(plan, param_leaves, b, f_idx,
                                    axis=axis, sharded=sharded)
            slot_bufs = tuple(slots[n][b] for n in self.slot_names)
            p_new, s_new = fused_bucket_update(
                grad_bufs[b], p_buf, slot_bufs, scal, rule=self.rule,
                hyper=self.hyper, impl=self.impl, interpret=self.interpret)
            for n, v in zip(self.slot_names, s_new):
                new_slots[n][b] = v
            if scatter and not padded:
                parts = plan.leaf_buffers(b, p_new, layout="shard")
            elif scatter:
                full = jax.lax.all_gather(p_new, axis, tiled=True)
                parts = plan.leaf_buffers(b, full, layout="gathered")
            else:
                parts = plan.leaf_buffers(b, p_new, layout="full")
            for i, v in parts.items():
                new_leaves[i] = v
        return new_leaves, new_slots, gnorm

    def region_collectives(self, plan: GradBuckets, *,
                           sharded: bool = True,
                           axis: str = FSDP
                           ) -> List[Tuple[str, Tuple[str, ...], int, str]]:
        """The collectives :meth:`region_apply` itself issues, as
        ``(kind, axes, nbytes, note)`` tuples — the fused plane's
        contribution to the static analyzer's planned set (the scalar
        grad-norm psums are below any audit threshold and deliberately
        omitted): one param ``all_gather`` per PADDED scatter bucket
        (uneven leaves exit the region whole, so their updated params
        re-gather once)."""
        out: List[Tuple[str, Tuple[str, ...], int, str]] = []
        if not sharded:
            return out
        for b in range(plan.n_buckets):
            if plan._is_scatter(b) and plan._is_padded(b):
                out.append(("all_gather", (axis,), plan.bucket_nbytes[b],
                            f"bucket {b} padded param re-gather"))
        return out

    def record(self, tag: str, plan: GradBuckets, **extra) -> None:
        """Bank the update schedule into ``profiler.update_report()``."""
        _record(tag, rule=self.rule, impl=self.resolved_impl(),
                n_buckets=plan.n_buckets,
                n_scatter_buckets=plan.n_scatter_buckets,
                bucket_nbytes=list(plan.bucket_nbytes),
                slot_names=list(self.slot_names),
                slot_bytes=4 * sum(plan.bucket_numel)
                * len(self.slot_names),
                clip_norm=self.clip_norm,
                weight_decay=self.weight_decay, **extra)


def fused_update_step(fused: FusedOptimizer, params: Any, grads: Any,
                      opt_state: Dict[str, Any],
                      mesh: Optional[Mesh] = None, *,
                      plan: Optional[GradBuckets] = None,
                      param_specs: Optional[Any] = None
                      ) -> Tuple[Any, Dict[str, Any], jax.Array]:
    """Standalone leaf-major entry: pack ``grads`` into the plan's bucket
    buffers and run the fused update — the optax pin / bench surface
    (``make_accum_train_step(update="fused_bucket")`` fuses the same
    :meth:`~FusedOptimizer.region_apply` into its accum region so the
    grads never leave the bucket domain at all).

    Returns ``(new_params, new_opt_state, grad_norm)``. Under ``jit`` the
    plan (and, for sharded plans, ``param_specs``) must be passed in —
    they are derived from committed shardings, which tracers don't carry.
    Grads enter the region LEAF-major (same boundary layout as the
    params) and are packed per device inside it — bucket buffers are
    never materialized in the global GSPMD domain.
    """
    from tony_tpu import compat
    from tony_tpu.parallel import overlap

    if plan is None:
        plan = fused.plan_for(params, mesh)
    fused.check_slots(plan, opt_state["slots"])
    count_inc = opt_state["count"] + 1
    scal = fused.scalars(count_inc)
    fused.record("fused_update", plan)
    sharded = plan.shard_size > 1 and mesh is not None

    def apply_local(p_leaves, g_leaves, sl, sc, f_idx_needed: bool):
        g_bufs = [fused.local_pack(plan, g_leaves, b,
                                   jax.lax.axis_index(FSDP)
                                   if (f_idx_needed and plan._is_scatter(b)
                                       and plan._is_padded(b)) else None,
                                   sharded=sharded)
                  for b in range(plan.n_buckets)]
        return fused.region_apply(plan, p_leaves, g_bufs, sl, sc,
                                  sharded=sharded)

    if not sharded:
        new_leaves, new_slots, gnorm = apply_local(
            jax.tree.leaves(params), jax.tree.leaves(grads),
            opt_state["slots"], scal, False)
        new_params = jax.tree.unflatten(plan.treedef, new_leaves)
        return new_params, {"count": count_inc, "slots": new_slots}, gnorm

    if param_specs is None:
        param_specs = overlap.fsdp_param_specs(params, mesh)
    if param_specs is None:
        raise ValueError(
            "fused_update_step: the plan has scatter buckets but no fsdp "
            "layout was detected on the params — pass param_specs")
    p_specs, _ = overlap.region_param_specs(plan, param_specs)
    b_specs = fused.bucket_specs(plan)
    slot_specs = {n: list(b_specs) for n in fused.slot_names}

    def spmd(p, g, sl, sc):
        new_leaves, new_slots, gnorm = apply_local(
            jax.tree.leaves(p), jax.tree.leaves(g), sl, sc, True)
        return (jax.tree.unflatten(plan.treedef, new_leaves), new_slots,
                gnorm)

    new_params, new_slots, gnorm = compat.shard_map(
        spmd, mesh, in_specs=(p_specs, p_specs, slot_specs, P()),
        out_specs=(p_specs, slot_specs, P()))(
            params, grads, opt_state["slots"], scal)
    return new_params, {"count": count_inc, "slots": new_slots}, gnorm


# ---------------------------------------------------------------------------
# Leaf-major ⇄ bucket-major converters + the ckpt portability codec
# ---------------------------------------------------------------------------

def _host(x) -> np.ndarray:
    return np.asarray(jax.device_get(x))


def _np_unpack_bucket(plan: GradBuckets, b: int,
                      buf: np.ndarray) -> Dict[int, np.ndarray]:
    """Host-numpy twin of ``leaf_buffers`` (scatter buckets in the
    "gathered" layout, others "full"): whole unpadded leaves from one
    shard-major buffer, zero jax involvement."""
    idxs = plan.buckets[b]
    out: Dict[int, np.ndarray] = {}
    off = 0
    if plan._is_scatter(b):
        chunk = plan.bucket_numel[b] // plan.shard_size
        for i in idxs:
            shp = plan.shard_shape(i)
            n = int(np.prod(shp, dtype=np.int64))
            d = plan.shard_dims[i]
            full = np.concatenate(
                [buf[f * chunk + off:f * chunk + off + n].reshape(shp)
                 for f in range(plan.shard_size)], axis=d)
            if plan._pad(i):
                sl = [slice(None)] * full.ndim
                sl[d] = slice(0, plan.shapes[i][d])
                full = full[tuple(sl)]
            out[i] = full
            off += n
        return out
    for i in idxs:
        shp = plan.shapes[i]
        n = int(np.prod(shp, dtype=np.int64))
        out[i] = buf[off:off + n].reshape(shp)
        off += n
    return out


def _np_pack_bucket(plan: GradBuckets, b: int,
                    leaves: Sequence[np.ndarray]) -> np.ndarray:
    """Host-numpy twin of ``pack`` for one bucket: shard-major with
    zero-padded uneven leaves."""
    idxs = plan.buckets[b]
    if not plan._is_scatter(b):
        return np.concatenate(
            [np.asarray(leaves[i]).reshape(-1) for i in idxs])
    src = {}
    for i in idxs:
        a = np.asarray(leaves[i])
        if plan._pad(i):
            d = plan.shard_dims[i]
            widths = [(0, plan._pad(i) if k == d else 0)
                      for k in range(a.ndim)]
            a = np.pad(a, widths)
        src[i] = a
    parts = []
    for f in range(plan.shard_size):
        for i in idxs:
            d = plan.shard_dims[i]
            n = plan.padded_shape(i)[d] // plan.shard_size
            sl = [slice(None)] * src[i].ndim
            sl[d] = slice(f * n, (f + 1) * n)
            parts.append(src[i][tuple(sl)].reshape(-1))
    return np.concatenate(parts)


def slots_to_leaf_major(plan: GradBuckets,
                        slots: Dict[str, Sequence[jax.Array]]
                        ) -> Dict[str, Any]:
    """Bucket-resident slot buffers → per-slot pytrees shaped like the
    params (f32 moments as HOST numpy, leaf paths identical to the param
    tree) — the portable form the ckpt manifests carry. Conversion is
    pure host numpy over ``device_get`` copies: a ``P(fsdp)``-sharded
    scatter buffer is the full shard-major buffer globally, and slicing
    it apart host-side (a) keeps the jax-0.4 GSPMD partitioner out of
    the repack entirely (its resharding of concatenated slice chunks on
    multi-axis meshes is wrong — the same reason the step path only
    packs region-locally) and (b) never materializes the unsharded slots
    in device memory. Ckpt-boundary only; the step path never calls
    this. The encode still pays the slots' device→host pull on the
    saving thread — folding it into the async snapshot writer is a named
    follow-on."""
    out: Dict[str, Any] = {}
    for name, bufs in slots.items():
        leaves: List[Any] = [None] * len(plan.shapes)
        for b in range(plan.n_buckets):
            for i, v in _np_unpack_bucket(plan, b,
                                          _host(bufs[b])).items():
                leaves[i] = v
        out[name] = jax.tree.unflatten(plan.treedef, leaves)
    return out


def leaf_major_to_slots(plan: GradBuckets, trees: Dict[str, Any],
                        mesh: Optional[Mesh] = None
                        ) -> Dict[str, List[jax.Array]]:
    """Inverse of :func:`slots_to_leaf_major` onto THIS plan's buckets:
    host-numpy re-pack (re-zero-padding uneven leaves) shard-major, then
    each scatter buffer is placed DIRECTLY into the scatter layout on
    ``mesh`` — devices receive only their chunk, the full buffer exists
    on host alone. The plan may belong to a different topology than the
    one that wrote the leaf-major form — that is the elastic-restore
    path."""
    out: Dict[str, List[jax.Array]] = {}
    for name, tree in trees.items():
        host_leaves = [_host(l) for l in jax.tree.leaves(tree)]
        bufs: List[Any] = []
        for b in range(plan.n_buckets):
            buf = _np_pack_bucket(plan, b, host_leaves)
            if mesh is not None:
                buf = jax.device_put(buf, NamedSharding(
                    mesh, P(FSDP) if plan._is_scatter(b) else P()))
            else:
                buf = jnp.asarray(buf)
            bufs.append(buf)
        out[name] = bufs
    return out


def is_fused_state(state: Any) -> bool:
    """A TrainState driven by this plane: ``tx`` is a FusedOptimizer and
    the opt state is a count+slots (or count+leaf portable) dict."""
    return isinstance(getattr(state, "tx", None), FusedOptimizer) \
        and isinstance(getattr(state, "opt_state", None), dict) \
        and "count" in state.opt_state


def _mesh_of(params: Any) -> Optional[Mesh]:
    for leaf in jax.tree.leaves(params):
        mesh = getattr(getattr(leaf, "sharding", None), "mesh", None)
        if mesh is not None and getattr(mesh, "axis_names", None):
            return mesh
    return None


def encode_state(state: Any) -> Any:
    """Ckpt codec, encode half: bucket-resident → portable leaf-major
    (``{"count", "leaf": {slot: param-shaped tree}}``). The manifest then
    records topology-independent leaf paths/shapes/specs, so the existing
    elastic-restore machinery handles fused states unchanged."""
    if not is_fused_state(state) or "slots" not in state.opt_state:
        return state
    plan = state.tx.plan_for(state.params, _mesh_of(state.params))
    state.tx.check_slots(plan, state.opt_state["slots"])
    return state.replace(opt_state={
        "count": state.opt_state["count"],
        "leaf": slots_to_leaf_major(plan, state.opt_state["slots"])})


def decode_state(state: Any, mesh: Optional[Mesh] = None) -> Any:
    """Ckpt codec, decode half: portable leaf-major → bucket-resident,
    re-planned for THE CURRENT topology (``mesh``, defaulting to the
    params' committed mesh) — a state written at fsdp=4 restores onto
    fsdp=2 with its moments re-bucketed into the new scatter layout."""
    if not is_fused_state(state) or "leaf" not in state.opt_state:
        return state
    if mesh is None:
        mesh = _mesh_of(state.params)
    plan = state.tx.plan_for(state.params, mesh)
    count = state.opt_state["count"]
    if mesh is not None:
        # The restored scalar may sit on a single device; the step jit
        # needs every state leaf on one device set.
        count = jax.device_put(jnp.asarray(_host(count), jnp.int32),
                               NamedSharding(mesh, P()))
    return state.replace(opt_state={
        "count": count,
        "slots": leaf_major_to_slots(plan, state.opt_state["leaf"], mesh)})


def _register_codec() -> None:
    from tony_tpu import ckpt

    ckpt.register_portable_codec(
        "fused_optim",
        lambda tree: is_fused_state(tree),
        encode_state, decode_state)


_register_codec()
