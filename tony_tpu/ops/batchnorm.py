"""Fused BatchNorm(+residual-add)(+ReLU) pallas kernels.

Why this exists (VERDICT r3 #1): the ResNet-50 bench's device trace blames
51.3% of step time on BatchNorm statistics + backward reductions — 105
`convert_reduce` XLA fusions that re-read every conv output (bf16→f32) for
mean/var forward and dβ/dγ/dx backward, plus separate relu-backward and
x̂ materializations. These kernels collapse the whole BN+add+ReLU epilogue
into the minimum number of HBM passes:

* forward: ONE stats pass (per-channel Σy and Σy² in a single read) and
  ONE normalize+add+relu pass (read y [+residual], write out);
* backward: ONE reduce pass producing dβ=Σg and dγ=Σg·x̂ — which are
  exactly the two correction terms the dx formula needs — and ONE dx pass
  (dx = γ·inv_σ·(g − dβ/M − x̂·dγ/M), plus dresidual=g for the add
  variant). The ReLU mask is recomputed from y (and γ,β,μ,σ) in-kernel,
  so no mask tensor and no saved x̂ ever touch HBM.

Everything is VPU work over a [M, C] view (M = N·H·W rows, channels in
lanes); accumulators ride the sequential TPU grid in f32. Shapes that
don't tile cleanly return None from :func:`pick_block_rows` and callers
fall back to the plain flax path.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Total VMEM budget across every row-blocked buffer of the op's WORST
# kernel (the dx pass), counting pallas's double buffering — the 16 MB
# VMEM must also hold the channel-vector operands and headroom.
_VMEM_BUDGET = 8 << 20


def pick_block_rows(m: int, c: int, itemsize: int = 2,
                    n_bufs: int = 3, n_temps: int = 8) -> Optional[int]:
    """Largest power-of-two row block that divides M and keeps the worst
    kernel within the VMEM budget: ``n_bufs`` double-buffered [bm, C]
    io blocks PLUS ``n_temps`` single-buffered f32 [bm, C] stack
    temporaries (xf/x̂/pre/g/dx… — Mosaic allocates kernel intermediates
    on the VMEM stack, and at bf16 io the f32 temps dominate).
    None = no clean tiling (caller falls back to XLA BatchNorm)."""
    per_row = 2 * n_bufs * c * itemsize + n_temps * c * 4
    # No floor: if even 16 rows exceed the budget (very wide C), every
    # candidate must fail so the caller takes the XLA fallback instead of
    # dispatching a kernel that OOMs VMEM at Mosaic compile time.
    limit = _VMEM_BUDGET // per_row
    for bm in (8192, 4096, 2048, 1024, 512, 256, 128, 64, 32, 16):
        if bm <= limit and m % bm == 0:
            return bm
    return None


def _stats_kernel(x_ref, out_ref):
    i = pl.program_id(0)
    xf = x_ref[...].astype(jnp.float32)
    # packsite: region-local — pallas kernel body; per-tile VMEM refs,
    # no GSPMD shardings exist here.
    part = jnp.concatenate([
        jnp.sum(xf, axis=0, keepdims=True),
        jnp.sum(xf * xf, axis=0, keepdims=True)], axis=0)   # [2, C]

    @pl.when(i == 0)
    def _():
        out_ref[...] = part

    @pl.when(i > 0)
    def _():
        out_ref[...] += part


def _bn_sums(x2d: jax.Array, bm: int, interpret: bool) -> jax.Array:
    m, c = x2d.shape
    return pl.pallas_call(
        _stats_kernel,
        grid=(m // bm,),
        in_specs=[pl.BlockSpec((bm, c), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((2, c), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((2, c), jnp.float32),
        interpret=interpret,
    )(x2d)


def _pre_act(x_ref, stats_ref, gb_ref, eps):
    """Normalized pre-activation x̂·γ+β (f32) and x̂, from the raw input —
    the shared recompute used by apply and both backward kernels."""
    mean = stats_ref[0:1, :]
    inv = jax.lax.rsqrt(stats_ref[1:2, :] + eps)
    xhat = (x_ref[...].astype(jnp.float32) - mean) * inv
    pre = xhat * gb_ref[0:1, :] + gb_ref[1:2, :]
    return pre, xhat, inv


def _apply_kernel(x_ref, stats_ref, gb_ref, out_ref, *, eps, relu):
    pre, _, _ = _pre_act(x_ref, stats_ref, gb_ref, eps)
    if relu:
        pre = jnp.maximum(pre, 0.0)
    out_ref[...] = pre.astype(out_ref.dtype)


def _apply_res_kernel(x_ref, res_ref, stats_ref, gb_ref, out_ref, *,
                      eps, relu):
    pre, _, _ = _pre_act(x_ref, stats_ref, gb_ref, eps)
    pre = pre + res_ref[...].astype(jnp.float32)
    if relu:
        pre = jnp.maximum(pre, 0.0)
    out_ref[...] = pre.astype(out_ref.dtype)


def _bwd_reduce_kernel(dy_ref, x_ref, stats_ref, gb_ref, out_ref, *,
                       eps, relu):
    i = pl.program_id(0)
    pre, xhat, _ = _pre_act(x_ref, stats_ref, gb_ref, eps)
    g = dy_ref[...].astype(jnp.float32)
    if relu:
        g = jnp.where(pre > 0, g, 0.0)
    # packsite: region-local — pallas kernel body (per-tile VMEM refs).
    part = jnp.concatenate([
        jnp.sum(g, axis=0, keepdims=True),             # dβ
        jnp.sum(g * xhat, axis=0, keepdims=True)], axis=0)   # dγ

    @pl.when(i == 0)
    def _():
        out_ref[...] = part

    @pl.when(i > 0)
    def _():
        out_ref[...] += part


def _bwd_reduce_res_kernel(dy_ref, x_ref, res_ref, stats_ref, gb_ref,
                           out_ref, *, eps, relu):
    i = pl.program_id(0)
    pre, xhat, _ = _pre_act(x_ref, stats_ref, gb_ref, eps)
    g = dy_ref[...].astype(jnp.float32)
    if relu:
        pre = pre + res_ref[...].astype(jnp.float32)
        g = jnp.where(pre > 0, g, 0.0)
    # packsite: region-local — pallas kernel body (per-tile VMEM refs).
    part = jnp.concatenate([
        jnp.sum(g, axis=0, keepdims=True),
        jnp.sum(g * xhat, axis=0, keepdims=True)], axis=0)

    @pl.when(i == 0)
    def _():
        out_ref[...] = part

    @pl.when(i > 0)
    def _():
        out_ref[...] += part


def _bwd_dx_kernel(dy_ref, x_ref, stats_ref, gb_ref, red_ref, dx_ref, *,
                   eps, relu, minv):
    pre, xhat, inv = _pre_act(x_ref, stats_ref, gb_ref, eps)
    g = dy_ref[...].astype(jnp.float32)
    if relu:
        g = jnp.where(pre > 0, g, 0.0)
    scale = gb_ref[0:1, :] * inv
    dx = scale * (g - red_ref[0:1, :] * minv - xhat * red_ref[1:2, :] * minv)
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _bwd_dx_res_kernel(dy_ref, x_ref, res_ref, stats_ref, gb_ref, red_ref,
                       dx_ref, dres_ref, *, eps, relu, minv):
    pre, xhat, inv = _pre_act(x_ref, stats_ref, gb_ref, eps)
    g = dy_ref[...].astype(jnp.float32)
    if relu:
        pre = pre + res_ref[...].astype(jnp.float32)
        g = jnp.where(pre > 0, g, 0.0)
    dres_ref[...] = g.astype(dres_ref.dtype)
    scale = gb_ref[0:1, :] * inv
    dx = scale * (g - red_ref[0:1, :] * minv - xhat * red_ref[1:2, :] * minv)
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _row_spec(bm, c):
    return pl.BlockSpec((bm, c), lambda i: (i, 0))


def _chan_spec(c):
    return pl.BlockSpec((2, c), lambda i: (0, 0))


# ---------------------------------------------------------------------------
# custom_vjp wrappers ([M, C] view; the flax module reshapes NHWC)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def bn_act_2d(x2d, gamma, beta, eps: float, relu: bool, bm: int,
              interpret: bool = False):
    """Fused train-mode BatchNorm(+ReLU) over [M, C]: returns
    ``(out, mean, var)`` — mean/var are batch statistics for the running
    averages (their cotangents are ignored; consumers stop-gradient them,
    and the batch-statistic chain rule is already inside the dx formula)."""
    out, mean, var, _, _ = _bn_act_fwd_impl(
        x2d, gamma, beta, None, eps, relu, bm, interpret)
    return out, mean, var


def _bn_act_fwd_impl(x2d, gamma, beta, res2d, eps, relu, bm, interpret):
    m, c = x2d.shape
    sums = _bn_sums(x2d, bm, interpret)
    mean = sums[0] / m
    var = jnp.maximum(sums[1] / m - mean * mean, 0.0)
    # packsite: region-local — [2, C] channel stats, replicated scalars
    # per channel; no shard-dim concat.
    stats = jnp.stack([mean, var])              # [2, C] f32
    gb = jnp.stack([gamma, beta]).astype(jnp.float32)  # packsite: region-local
    if res2d is None:
        out = pl.pallas_call(
            functools.partial(_apply_kernel, eps=eps, relu=relu),
            grid=(m // bm,),
            in_specs=[_row_spec(bm, c), _chan_spec(c), _chan_spec(c)],
            out_specs=_row_spec(bm, c),
            out_shape=jax.ShapeDtypeStruct((m, c), x2d.dtype),
            interpret=interpret,
        )(x2d, stats, gb)
    else:
        out = pl.pallas_call(
            functools.partial(_apply_res_kernel, eps=eps, relu=relu),
            grid=(m // bm,),
            in_specs=[_row_spec(bm, c), _row_spec(bm, c), _chan_spec(c),
                      _chan_spec(c)],
            out_specs=_row_spec(bm, c),
            out_shape=jax.ShapeDtypeStruct((m, c), x2d.dtype),
            interpret=interpret,
        )(x2d, res2d, stats, gb)
    return out, mean, var, stats, gb


def _bn_act_fwd(x2d, gamma, beta, eps, relu, bm, interpret):
    out, mean, var, stats, gb = _bn_act_fwd_impl(
        x2d, gamma, beta, None, eps, relu, bm, interpret)
    return (out, mean, var), (x2d, stats, gb)


def _bn_act_bwd(eps, relu, bm, interpret, saved, cts):
    dy, _, _ = cts          # mean/var feed only stop-gradient'd consumers
    x2d, stats, gb = saved
    m, c = x2d.shape
    red = pl.pallas_call(
        functools.partial(_bwd_reduce_kernel, eps=eps, relu=relu),
        grid=(m // bm,),
        in_specs=[_row_spec(bm, c), _row_spec(bm, c), _chan_spec(c),
                  _chan_spec(c)],
        out_specs=_chan_spec(c),
        out_shape=jax.ShapeDtypeStruct((2, c), jnp.float32),
        interpret=interpret,
    )(dy, x2d, stats, gb)
    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, eps=eps, relu=relu, minv=1.0 / m),
        grid=(m // bm,),
        in_specs=[_row_spec(bm, c), _row_spec(bm, c), _chan_spec(c),
                  _chan_spec(c), _chan_spec(c)],
        out_specs=_row_spec(bm, c),
        out_shape=jax.ShapeDtypeStruct((m, c), x2d.dtype),
        interpret=interpret,
    )(dy, x2d, stats, gb, red)
    # red = [Σg, Σg·x̂] = [dβ, dγ]; cotangent order follows (x, gamma, beta).
    return dx, red[1], red[0]


bn_act_2d.defvjp(_bn_act_fwd, _bn_act_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def bn_add_act_2d(x2d, gamma, beta, res2d, eps: float, relu: bool,
                  bm: int, interpret: bool = False):
    """Fused BatchNorm + residual add (+ReLU): ``relu(bn(x) + res)`` —
    the bottleneck-exit epilogue in one pass. Returns (out, mean, var)."""
    out, mean, var, _, _ = _bn_act_fwd_impl(
        x2d, gamma, beta, res2d, eps, relu, bm, interpret)
    return out, mean, var


def _bn_add_act_fwd(x2d, gamma, beta, res2d, eps, relu, bm, interpret):
    out, mean, var, stats, gb = _bn_act_fwd_impl(
        x2d, gamma, beta, res2d, eps, relu, bm, interpret)
    return (out, mean, var), (x2d, res2d, stats, gb)


def _bn_add_act_bwd(eps, relu, bm, interpret, saved, cts):
    dy, _, _ = cts
    x2d, res2d, stats, gb = saved
    m, c = x2d.shape
    red = pl.pallas_call(
        functools.partial(_bwd_reduce_res_kernel, eps=eps, relu=relu),
        grid=(m // bm,),
        in_specs=[_row_spec(bm, c), _row_spec(bm, c), _row_spec(bm, c),
                  _chan_spec(c), _chan_spec(c)],
        out_specs=_chan_spec(c),
        out_shape=jax.ShapeDtypeStruct((2, c), jnp.float32),
        interpret=interpret,
    )(dy, x2d, res2d, stats, gb)
    dx, dres = pl.pallas_call(
        functools.partial(_bwd_dx_res_kernel, eps=eps, relu=relu,
                          minv=1.0 / m),
        grid=(m // bm,),
        in_specs=[_row_spec(bm, c), _row_spec(bm, c), _row_spec(bm, c),
                  _chan_spec(c), _chan_spec(c), _chan_spec(c)],
        out_specs=(_row_spec(bm, c), _row_spec(bm, c)),
        out_shape=(jax.ShapeDtypeStruct((m, c), x2d.dtype),
                   jax.ShapeDtypeStruct((m, c), res2d.dtype)),
        interpret=interpret,
    )(dy, x2d, res2d, stats, gb, red)
    return dx, red[1], red[0], dres


bn_add_act_2d.defvjp(_bn_add_act_fwd, _bn_add_act_bwd)


def fused_bn_act(x: jax.Array, gamma: jax.Array, beta: jax.Array,
                 residual: Optional[jax.Array] = None, *,
                 eps: float = 1e-5, relu: bool = True,
                 interpret: bool = False,
                 ) -> Optional[Tuple[jax.Array, jax.Array, jax.Array]]:
    """NHWC (or any [..., C]) entry: train-mode fused BN(+add)(+ReLU).
    Returns ``(out, mean, var)`` or None when the shape has no clean
    tiling (caller must fall back to the XLA path)."""
    c = x.shape[-1]
    m = x.size // c
    # Worst kernel: the dx pass — (dy, x[, res]) in, (dx[, dres]) out.
    n_bufs = 3 if residual is None else 5
    bm = pick_block_rows(m, c, jnp.dtype(x.dtype).itemsize, n_bufs)
    if bm is None:
        return None
    x2d = x.reshape(m, c)
    if residual is None:
        out, mean, var = bn_act_2d(x2d, gamma, beta, eps, relu, bm,
                                   interpret)
    else:
        out, mean, var = bn_add_act_2d(x2d, gamma, beta,
                                       residual.reshape(m, c), eps, relu,
                                       bm, interpret)
    return out.reshape(x.shape), mean, var
